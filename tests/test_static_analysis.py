"""tpulint static-analysis tests.

Two layers:
- per-rule unit tests: each of JX001-JX006 on a purpose-built bad snippet
  (must fire) and a clean snippet (must not fire);
- the tier-1 gate: the CLI over the whole package must exit 0 against the
  checked-in baseline, and every baselined finding must carry a reason.
"""

import json
import os
import subprocess
import sys

from deeplearning4j_tpu.analysis import (
    ALL_RULES,
    Baseline,
    DEFAULT_BASELINE_PATH,
    lint_package,
    lint_source,
)


def rules_of(findings):
    return {f.rule for f in findings}


def lint(src, rules=None):
    return lint_source(src, "<snippet>", rules=rules)


# --------------------------------------------------------------- JX001

class TestJX001HostSync:
    def test_block_until_ready_under_jit_fires(self):
        src = """
import jax

@jax.jit
def step(x):
    y = x + 1
    y.block_until_ready()
    return y
"""
        fs = lint(src, ["JX001"])
        assert rules_of(fs) == {"JX001"}
        assert "block_until_ready" in fs[0].message

    def test_item_and_np_asarray_in_jit_called_helper_fire(self):
        # the violation is in a helper only *reachable* from a jitted fn —
        # exercises the call-graph closure, not just the decorated root
        src = """
import jax
import numpy as np

def helper(x):
    return np.asarray(x).item()

def step(x):
    return helper(x) + 1

fast = jax.jit(step)
"""
        fs = lint(src, ["JX001"])
        assert len(fs) == 2  # np.asarray + .item
        assert all(f.rule == "JX001" for f in fs)

    def test_float_on_param_fires_but_config_float_does_not(self):
        src = """
import jax

CONF = object()

@jax.jit
def step(x):
    lr = float(CONF.learning_rate)   # module config, not param-rooted: clean
    return x * float(x)              # traced param: fires
"""
        fs = lint(src, ["JX001"])
        assert len(fs) == 1
        assert fs[0].line == 9

    def test_host_side_np_asarray_is_clean(self):
        src = """
import numpy as np

def load(path):
    return np.asarray([1, 2, 3]).item()
"""
        assert lint(src, ["JX001"]) == []

    def test_suppression_comment_silences(self):
        src = """
import jax

@jax.jit
def step(x):
    return x.item()  # tpulint: disable=JX001
"""
        assert lint(src, ["JX001"]) == []


# --------------------------------------------------------------- JX002

class TestJX002SideEffects:
    def test_print_time_random_under_jit_fire(self):
        src = """
import jax
import time
import random
import numpy as np

@jax.jit
def step(x):
    print("step!")
    t = time.time()
    r = random.random()
    n = np.random.randn()
    return x + t + r + n
"""
        fs = lint(src, ["JX002"])
        assert len(fs) == 4
        assert rules_of(fs) == {"JX002"}

    def test_side_effects_outside_trace_are_clean(self):
        src = """
import time
import random

def host_loop():
    print("epoch", time.time(), random.random())
"""
        assert lint(src, ["JX002"]) == []

    def test_jax_random_is_clean(self):
        src = """
import jax

@jax.jit
def step(x, key):
    return x + jax.random.normal(key, x.shape)
"""
        assert lint(src, ["JX002"]) == []


# --------------------------------------------------------------- JX003

class TestJX003RetraceHazards:
    def test_jit_inside_loop_fires(self):
        src = """
import jax

def train(batches):
    out = []
    for b in batches:
        out.append(jax.jit(lambda x: x * 2)(b))
    return out
"""
        fs = lint(src, ["JX003"])
        assert any("inside a loop" in f.message for f in fs)

    def test_static_argnums_on_array_param_fires(self):
        src = """
import jax

def step(params, x):
    return x

fast = jax.jit(step, static_argnums=(1,))
"""
        fs = lint(src, ["JX003"])
        assert len(fs) == 1
        assert "`x` static" in fs[0].message

    def test_static_argnames_on_scalar_config_is_clean(self):
        src = """
import jax

def step(x, n_layers):
    return x * n_layers

fast = jax.jit(step, static_argnames=("n_layers",))
"""
        assert lint(src, ["JX003"]) == []

    def test_module_level_jit_is_clean(self):
        src = """
import jax

def step(x):
    return x * 2

fast = jax.jit(step)
"""
        assert lint(src, ["JX003"]) == []


# --------------------------------------------------------------- JX004

class TestJX004Float64:
    def test_f64_dtype_in_traced_code_fires(self):
        src = """
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    return x.astype(jnp.float64)
"""
        fs = lint(src, ["JX004"])
        assert len(fs) == 1
        assert "float64" in fs[0].message

    def test_host_side_f64_is_clean(self):
        src = """
import numpy as np

def serialize(params):
    return np.asarray(params, np.float64).tobytes()
"""
        assert lint(src, ["JX004"]) == []

    def test_x64_gated_f64_is_clean(self):
        src = """
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    dt = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    return x.astype(dt)
"""
        assert lint(src, ["JX004"]) == []


# --------------------------------------------------------------- JX005

class TestJX005ThreadSafety:
    BAD = """
import threading

class Manager:
    def __init__(self):
        self._lock = threading.Lock()
        self.progress = 0

    def start(self):
        t = threading.Thread(target=self._worker, daemon=True)
        t.start()

    def _worker(self):
        self.progress += 1

    def reset(self):
        self.progress = 0
"""

    def test_unlocked_cross_thread_write_fires(self):
        fs = lint(self.BAD, ["JX005"])
        assert len(fs) == 1
        assert "`self.progress`" in fs[0].message

    def test_locked_writes_are_clean(self):
        src = self.BAD.replace(
            "        self.progress += 1",
            "        with self._lock:\n            self.progress += 1",
        ).replace(
            "        self.progress = 0\n",
            "        with self._lock:\n            self.progress = 0\n", 1)
        # first replace targets _worker; also lock reset()
        src = src.replace(
            "    def reset(self):\n        self.progress = 0",
            "    def reset(self):\n        with self._lock:\n"
            "            self.progress = 0")
        assert lint(src, ["JX005"]) == []

    def test_nested_thread_target_is_seen(self):
        src = """
import threading

class Saver:
    def save(self):
        def work():
            self.last_error = "boom"
        threading.Thread(target=work, daemon=True).start()

    def check(self):
        self.last_error = None
"""
        fs = lint(src, ["JX005"])
        assert len(fs) == 1
        assert "last_error" in fs[0].message

    def test_threadless_class_is_clean(self):
        src = """
class Plain:
    def a(self):
        self.x = 1

    def b(self):
        self.x = 2
"""
        assert lint(src, ["JX005"]) == []


# --------------------------------------------------------------- JX006

class TestJX006DtypeSniff:
    def test_uint8_sniff_fires(self):
        src = """
import jax.numpy as jnp

def stage(x):
    if x.dtype == jnp.uint8:
        x = x / 255.0
    return x
"""
        fs = lint(src, ["JX006"])
        assert len(fs) == 1
        assert "uint8" in fs[0].message

    def test_uint8_as_storage_dtype_is_clean(self):
        src = """
import numpy as np

def load(buf):
    return np.frombuffer(buf, np.uint8)
"""
        assert lint(src, ["JX006"]) == []

    def test_preprocessors_module_is_allowed(self, tmp_path):
        src = """
import jax.numpy as jnp

def stage(x):
    if x.dtype == jnp.uint8:
        return x / 255.0
    return x
"""
        d = tmp_path / "nn" / "conf"
        d.mkdir(parents=True)
        p = d / "preprocessors.py"
        p.write_text(src)
        from deeplearning4j_tpu.analysis import lint_file
        assert [f for f in lint_file(str(p)) if f.rule == "JX006"] == []


class TestJX007AotOutsideCompilation:
    def test_lower_compile_chain_fires(self):
        src = """
import jax

def precompile(fn, x):
    return fn.lower(x).compile()
"""
        fs = lint(src, ["JX007"])
        assert len(fs) == 2  # the .lower(x) call and the .compile() call
        assert any(".lower" in f.message for f in fs)
        assert any(".compile" in f.message for f in fs)

    def test_jax_export_and_serialize_import_fire(self):
        src = """
import jax
from jax.experimental import serialize_executable

def ship(fn, x):
    return jax.export.export(jax.jit(fn))(x)
"""
        fs = lint(src, ["JX007"])
        assert any("serialize_executable" in f.message for f in fs)
        assert any("jax.export" in f.message for f in fs)

    def test_str_lower_and_re_compile_are_clean(self):
        src = """
import re

def normalize(name):
    return re.compile(r"\\s+").sub("-", name.lower())
"""
        assert lint(src, ["JX007"]) == []

    def test_compilation_package_is_allowed(self, tmp_path):
        src = """
def precompile(fn, x):
    return fn.lower(x).compile()
"""
        d = tmp_path / "compilation"
        d.mkdir(parents=True)
        p = d / "program.py"
        p.write_text(src)
        from deeplearning4j_tpu.analysis import lint_file
        assert [f for f in lint_file(str(p)) if f.rule == "JX007"] == []

    def test_profiler_probe_is_allowed(self, tmp_path):
        src = """
def probe(fn, x):
    return fn.lower(x).compile().cost_analysis()
"""
        d = tmp_path / "observability"
        d.mkdir(parents=True)
        p = d / "profiler.py"
        p.write_text(src)
        from deeplearning4j_tpu.analysis import lint_file
        assert [f for f in lint_file(str(p)) if f.rule == "JX007"] == []


class TestJX008MetricsInHotPath:
    def test_family_creation_in_jit_reachable_fires(self):
        src = """
import jax
from deeplearning4j_tpu import observability as obs

@jax.jit
def step(x):
    obs.metrics.counter("dl4j_steps_total", "steps").inc()
    return x + 1
"""
        fs = lint(src, ["JX008"])
        assert rules_of(fs) == {"JX008"}
        assert "jit-reachable" in fs[0].message

    def test_family_creation_in_loop_fires(self):
        src = """
from deeplearning4j_tpu import observability as obs

def train(batches):
    for b in batches:
        h = obs.metrics.histogram("dl4j_lat_seconds", "latency")
        h.observe(0.1)
"""
        fs = lint(src, ["JX008"])
        assert rules_of(fs) == {"JX008"}
        assert "per-iteration loop" in fs[0].message

    def test_self_registry_receiver_fires(self):
        src = """
class Worker:
    def run(self, items):
        while items:
            self._reg.gauge("dl4j_depth", "queue depth").set(len(items))
            items.pop()
"""
        fs = lint(src, ["JX008"])
        assert rules_of(fs) == {"JX008"}

    def test_module_level_and_cached_child_are_clean(self):
        src = """
from deeplearning4j_tpu import observability as obs

_M_STEPS = obs.metrics.counter("dl4j_steps_total", "steps",
                               label_names=("engine",)).labels(engine="mln")

def train(batches):
    for b in batches:
        _M_STEPS.inc()
"""
        assert lint(src, ["JX008"]) == []

    def test_non_registry_receiver_is_clean(self):
        # `.counter(...)` on something that does not look like a registry
        # (e.g. a collections.Counter factory) must not fire
        src = """
def tally(conn, rows):
    for r in rows:
        conn.counter("hits").bump()
"""
        assert lint(src, ["JX008"]) == []

    def test_one_shot_function_registration_is_clean(self):
        # straight-line registration in a setup function: neither jit-
        # reachable nor looped
        src = """
from deeplearning4j_tpu import observability as obs

def install(reg):
    return reg.histogram("dl4j_lat_seconds", "latency")
"""
        assert lint(src, ["JX008"]) == []


# --------------------------------------------------------------- JX010

class TestJX010PallasOutsideKernels:
    def test_pallas_import_fires(self):
        src = """
from jax.experimental import pallas as pl

def my_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]
"""
        fs = lint(src, ["JX010"])
        assert rules_of(fs) == {"JX010"}
        assert "kernels/" in fs[0].message

    def test_dotted_import_and_pallas_call_fire(self):
        src = """
import jax.experimental.pallas

def run(fn, x):
    return jax.experimental.pallas.pallas_call(fn, out_shape=x)(x)
"""
        fs = lint(src, ["JX010"])
        assert len(fs) == 2  # the import and the .pallas_call attribute
        assert any("import" in f.message for f in fs)
        assert any("pallas_call" in f.message for f in fs)

    def test_tpu_submodule_import_fires(self):
        src = """
from jax.experimental.pallas import tpu as pltpu
"""
        fs = lint(src, ["JX010"])
        assert rules_of(fs) == {"JX010"}

    def test_registry_dispatch_is_clean(self):
        src = """
from deeplearning4j_tpu.kernels import registry

def resolve(shapes, dtypes):
    return registry.resolve("lstm_cell", shapes=shapes, dtypes=dtypes)
"""
        assert lint(src, ["JX010"]) == []

    def test_kernels_package_is_allowed(self, tmp_path):
        src = """
from jax.experimental import pallas as pl

def build(fn, out):
    return pl.pallas_call(fn, out_shape=out)
"""
        d = tmp_path / "kernels"
        d.mkdir(parents=True)
        p = d / "lstm_cell.py"
        p.write_text(src)
        from deeplearning4j_tpu.analysis import lint_file
        assert [f for f in lint_file(str(p)) if f.rule == "JX010"] == []


# --------------------------------------------------------------- JX011

class TestJX011SyncStagingInFitLoop:
    # JX011 is path-scoped (nn/, parallel/, datasets/), so snippets lint
    # under an explicit in-scope path instead of "<snippet>".
    def _lint(self, src, path="nn/fake_engine.py"):
        return lint_source(src, path, rules=["JX011"])

    def test_stage_to_device_in_fit_fires(self):
        src = """
from deeplearning4j_tpu.datasets.staging import stage_to_device

class Net:
    def fit(self, iterator):
        for ds in iterator:
            staged = stage_to_device(ds)
            self._fit_dispatch(staged)
"""
        fs = self._lint(src)
        assert rules_of(fs) == {"JX011"}
        assert "staging.py" in fs[0].message

    def test_device_put_in_dispatch_fires(self):
        src = """
import jax

class Wrapper:
    def _fit_dispatch(self, ds):
        x = jax.device_put(ds.features)
        return self.step(x)
"""
        fs = self._lint(src, path="parallel/fake_wrapper.py")
        assert rules_of(fs) == {"JX011"}
        assert "device_put" in fs[0].message

    def test_scalar_put_is_exempt(self):
        src = """
import jax
import numpy as np

class Net:
    def _fit_tbptt(self, ds):
        eb = jax.device_put(np.float32(2.0))
        return eb
"""
        assert self._lint(src) == []

    def test_staged_consumption_is_clean(self):
        src = """
from deeplearning4j_tpu.datasets import staging as _staging

class Net:
    def fit(self, iterator):
        src = _staging.maybe_stage(iterator, net=self, engine="mln")
        try:
            for ds in src:
                self._fit_dispatch(ds)
        finally:
            _staging.close_stager(src)
"""
        assert self._lint(src) == []

    def test_cold_path_helper_is_clean(self):
        # Puts outside fit/dispatch-named functions (warmup, cache build)
        # are not hot-path stalls.
        src = """
import jax

class Wrapper:
    def warmup(self, batch):
        return jax.device_put(batch.features)
"""
        assert self._lint(src, path="parallel/fake_wrapper.py") == []

    def test_staging_module_is_allowed(self):
        src = """
import jax

def fit(parts):
    return jax.device_put(tuple(parts))
"""
        assert self._lint(
            src, path="deeplearning4j_tpu/datasets/staging.py") == []

    def test_package_is_jx011_clean(self):
        from deeplearning4j_tpu.analysis.linter import lint_package
        assert [f for f in lint_package(rules=["JX011"])] == []


# --------------------------------------------------------------- JX012

class TestJX012UnboundedBlockingIO:
    # JX012 is path-scoped to serving/ and parallel/ — the layers where a
    # hung socket propagates to the whole fleet.
    def _lint(self, src, path="serving/fake_router.py"):
        return lint_source(src, path, rules=["JX012"])

    def test_unbounded_calls_fire(self):
        src = """
import socket
import urllib.request
import requests

def fetch(addr, url):
    s = socket.create_connection(addr)
    r = urllib.request.urlopen(url)
    q = requests.get(url)
"""
        fs = self._lint(src)
        assert rules_of(fs) == {"JX012"}
        assert len(fs) == 3
        assert any("timeout" in f.message for f in fs)

    def test_http_client_ctor_fires(self):
        src = """
import http.client

def probe(host):
    return http.client.HTTPConnection(host, 8080)
"""
        fs = self._lint(src, path="parallel/fake_probe.py")
        assert rules_of(fs) == {"JX012"}

    def test_explicit_timeouts_are_clean(self):
        src = """
import socket
import urllib.request
import requests

def fetch(addr, url):
    s = socket.create_connection(addr, timeout=2.0)
    r = urllib.request.urlopen(url, timeout=1.0)
    q = requests.get(url, timeout=3)
"""
        assert self._lint(src) == []

    def test_positional_timeout_is_clean(self):
        src = """
import socket

def fetch(addr):
    return socket.create_connection(addr, 2.0)
"""
        assert self._lint(src) == []

    def test_out_of_scope_path_is_clean(self):
        src = """
import urllib.request

def fetch(url):
    return urllib.request.urlopen(url)
"""
        assert self._lint(src, path="datasets/fake_fetch.py") == []

    def test_package_is_jx012_clean(self):
        # The router, replica runtime and coordinator client must carry
        # explicit deadlines on every blocking call they make.
        from deeplearning4j_tpu.analysis.linter import lint_package
        assert [f for f in lint_package(rules=["JX012"])] == []


# --------------------------------------------------------------- JX013

class TestJX013TracePropagation:
    # JX013 is path-scoped like JX012: serving/ and parallel/ are the
    # layers whose outbound hops must stay on the request's span tree.
    def _lint(self, src, path="serving/fake_hop.py"):
        return lint_source(src, path, rules=["JX013"])

    def test_raw_outbound_call_fires(self):
        src = """
import urllib.request

def forward(url, data):
    with urllib.request.urlopen(url, data=data, timeout=1.0) as r:
        return r.read()
"""
        fs = self._lint(src)
        assert rules_of(fs) == {"JX013"}
        assert any("X-DL4J-Trace" in f.message for f in fs)

    def test_requests_verb_fires(self):
        src = """
import requests

def forward(url, doc):
    return requests.post(url, json=doc, timeout=1.0)
"""
        fs = self._lint(src, path="parallel/fake_rpc.py")
        assert rules_of(fs) == {"JX013"}

    def test_trace_evidence_is_clean(self):
        src = """
import urllib.request
from deeplearning4j_tpu.observability import propagate as _prop

def forward(url, data):
    req = urllib.request.Request(
        url, data=data, headers=_prop.trace_headers())
    with urllib.request.urlopen(req, timeout=1.0) as r:
        return r.read()
"""
        assert self._lint(src) == []

    def test_header_literal_is_clean(self):
        src = """
import urllib.request

def forward(url, data, header_value):
    req = urllib.request.Request(
        url, data=data, headers={"X-DL4J-Trace": header_value})
    with urllib.request.urlopen(req, timeout=1.0) as r:
        return r.read()
"""
        assert self._lint(src) == []

    def test_scrape_allowlist_is_clean(self):
        # Metrics scrapes (router load poll, federation aggregator) are
        # trace roots, not request hops: nothing to forward.
        src = """
import urllib.request

def scrape_member(url):
    with urllib.request.urlopen(url, timeout=1.0) as r:
        return r.read()

def get_text(url, timeout_s):
    with urllib.request.urlopen(url, timeout=timeout_s) as r:
        return r.read().decode()
"""
        assert self._lint(src) == []

    def test_out_of_scope_path_is_clean(self):
        src = """
import urllib.request

def forward(url):
    return urllib.request.urlopen(url, timeout=1.0)
"""
        assert self._lint(src, path="datasets/fake_fetch.py") == []

    def test_package_is_jx013_clean(self):
        # Every outbound hop in serving/ and parallel/ must propagate
        # (post_json) or be a legitimate scrape (get_text, _scrape_*).
        from deeplearning4j_tpu.analysis.linter import lint_package
        assert [f for f in lint_package(rules=["JX013"])] == []


# --------------------------------------------------------------- JX014

class TestJX014DenseKVAllocation:
    def _lint(self, src, path="deeplearning4j_tpu/nn/fake_layer.py"):
        return lint_source(src, path, rules=["JX014"])

    def test_direct_allocation_fires(self):
        src = """
import jax.numpy as jnp

def alloc_cache(conf, slots, H, D):
    return jnp.zeros((slots, conf.decode_cache_length, H, D))
"""
        fs = self._lint(src)
        assert rules_of(fs) == {"JX014"}
        assert "kv_pool" in fs[0].message

    def test_aliased_allocation_fires(self):
        # One aliasing hop: L = conf.decode_cache_length, then zeros((L,))
        src = """
import jax.numpy as jnp

def alloc_cache(conf, slots, H, D):
    L = conf.decode_cache_length
    return jnp.zeros((slots, L, H, D))
"""
        fs = self._lint(src)
        assert rules_of(fs) == {"JX014"}

    def test_page_granular_allocation_is_clean(self):
        src = """
import jax.numpy as jnp

def alloc_pages(pool, H, D):
    return jnp.zeros((pool.num_pages, pool.page_size, H, D))
"""
        assert self._lint(src) == []

    def test_pool_module_is_exempt(self):
        src = """
import numpy as np

def table(conf, slots):
    per = conf.decode_cache_length // 64
    return np.zeros((slots, per), np.int32)
"""
        assert self._lint(
            src, path="deeplearning4j_tpu/models/kv_pool.py") == []
        assert rules_of(self._lint(src)) == {"JX014"}

    def test_package_is_clean(self):
        # The shipped decode path is page-granular (attention primes via
        # jnp.pad; the steppers size state from templates / pool geometry).
        assert [f for f in lint_package(rules=["JX014"])] == []


# --------------------------------------------------------------- JX015

class TestJX015FrozenLeafTraining:
    def _lint(self, src, path="deeplearning4j_tpu/nn/fake_trainer.py"):
        return lint_source(src, path, rules=["JX015"])

    def test_grad_over_handrolled_lora_split_fires(self):
        src = """
import jax

def step(net, params, x, y):
    trainable = {k: v for k, v in params.items() if "__lora_" in k}
    loss, grads = jax.value_and_grad(net.loss)(trainable, x, y)
    return grads
"""
        fs = self._lint(src)
        assert rules_of(fs) == {"JX015"}
        assert "frozen_spec" in fs[0].message

    def test_updater_init_over_frozen_leaves_fires(self):
        src = """
def build_opt(updaters, layers, params):
    out = {}
    for lk, layer in zip(params, layers):
        if layer.frozen:
            pass  # handled by hand below
        out[lk] = updaters[lk].init(params[lk])
    return out
"""
        fs = self._lint(src)
        assert rules_of(fs) == {"JX015"}
        assert ".init(" in fs[0].message

    def test_grad_without_markers_is_clean(self):
        # Ordinary training code: no frozen/LoRA handling in sight.
        src = """
import jax

def step(net, params, x, y):
    loss, grads = jax.value_and_grad(net.loss)(params, x, y)
    return grads
"""
        assert self._lint(src) == []

    def test_markers_without_train_op_are_clean(self):
        # Serving-side merge code touches lora leaves but never trains.
        src = """
def merged(base, adapter):
    return {k: v for k, v in base.items() if "__lora_" not in k}
"""
        assert self._lint(src) == []

    def test_seam_modules_are_exempt(self):
        src = """
import jax

def refit(conf, params, loss):
    if conf.lora_rank:
        return jax.grad(loss)(params)
"""
        assert self._lint(
            src, path="deeplearning4j_tpu/nn/transfer.py") == []
        assert self._lint(
            src, path="deeplearning4j_tpu/nn/lora.py") == []
        assert rules_of(self._lint(src)) == {"JX015"}

    def test_package_is_clean(self):
        # The engines consume the freeze seam through transfer.frozen_spec
        # / split_tree and never spell the marker names next to a grad.
        assert [f for f in lint_package(rules=["JX015"])] == []


# --------------------------------------------------------------- JX016

class TestJX016UnboundedLabelCardinality:
    def _lint(self, src, path="deeplearning4j_tpu/serving/fake_server.py"):
        return lint_source(src, path, rules=["JX016"])

    def test_request_id_label_fires(self):
        src = """
def handle(counter, request_id, model):
    counter.labels(model=model, request=str(request_id)).inc()
"""
        fs = self._lint(src)
        assert rules_of(fs) == {"JX016"}
        assert "request_id" in fs[0].message

    def test_prompt_in_fstring_label_fires(self):
        src = """
def handle(counter, prompt):
    counter.labels(key=f"p:{prompt[:8]}").inc()
"""
        assert rules_of(self._lint(src)) == {"JX016"}

    def test_stringified_exception_label_fires(self):
        src = """
def handle(counter, fn):
    try:
        fn()
    except Exception as e:
        counter.labels(reason=str(e)).inc()
"""
        fs = self._lint(src)
        assert rules_of(fs) == {"JX016"}
        assert "exception" in fs[0].message

    def test_bare_exception_label_fires(self):
        src = """
def handle(counter, fn):
    try:
        fn()
    except Exception as e:
        counter.labels(reason=e).inc()
"""
        assert rules_of(self._lint(src)) == {"JX016"}

    def test_bounded_vocabularies_are_clean(self):
        # The in-tree shapes: an adapter name drawn from the loaded
        # registry, a reason capped to its prefix, and an exception fed
        # to a CLASSIFIER that returns an outcome enum.
        src = """
def count(counter, model, adapter, reason, outcome_of, fn):
    counter.labels(model=model, adapter=str(adapter)).inc()
    counter.labels(reason=reason.split(":", 1)[0]).inc()
    try:
        fn()
    except Exception as e:
        counter.labels(outcome=outcome_of(e)).inc()
"""
        assert self._lint(src) == []

    def test_package_is_clean(self):
        # Serving/observability label per-request detail via the ledger
        # and spans, never via metric labels.
        assert [f for f in lint_package(rules=["JX016"])] == []


# ------------------------------------------------------------ framework

# --------------------------------------------------------------- JX017

class TestJX017LockOrderInversion:
    def test_opposite_with_nesting_fires(self):
        src = """
import threading

class Transfer:
    def __init__(self):
        self._src = threading.Lock()
        self._dst = threading.Lock()

    def push(self):
        with self._src:
            with self._dst:
                pass

    def pull(self):
        with self._dst:
            with self._src:
                pass
"""
        fs = lint(src, ["JX017"])
        assert rules_of(fs) == {"JX017"}
        assert len(fs) == 1  # one cycle, reported once
        assert "Transfer._src" in fs[0].message
        assert "Transfer._dst" in fs[0].message
        assert "push" in fs[0].message and "pull" in fs[0].message

    def test_inversion_through_callee_fires(self):
        # push takes src then dst directly; pull holds dst and CALLS a
        # helper that takes src — the cycle only exists interprocedurally
        src = """
import threading

class Transfer:
    def __init__(self):
        self._src = threading.Lock()
        self._dst = threading.Lock()

    def _grab_src(self):
        with self._src:
            pass

    def push(self):
        with self._src:
            with self._dst:
                pass

    def pull(self):
        with self._dst:
            self._grab_src()
"""
        fs = lint(src, ["JX017"])
        assert rules_of(fs) == {"JX017"}
        assert "_grab_src" in fs[0].message

    def test_consistent_order_is_clean(self):
        src = """
import threading

class Transfer:
    def __init__(self):
        self._src = threading.Lock()
        self._dst = threading.Lock()

    def push(self):
        with self._src:
            with self._dst:
                pass

    def pull(self):
        with self._src:
            with self._dst:
                pass
"""
        assert lint(src, ["JX017"]) == []

    def test_reentrant_same_lock_is_clean(self):
        src = """
import threading

class Host:
    def __init__(self):
        self._lock = threading.RLock()

    def outer(self):
        with self._lock:
            self.inner()

    def inner(self):
        with self._lock:
            pass
"""
        assert lint(src, ["JX017"]) == []


# --------------------------------------------------------------- JX018

class TestJX018BlockingUnderLock:
    def test_sleep_under_lock_fires(self):
        src = """
import threading
import time

class Registry:
    def __init__(self):
        self._lock = threading.Lock()

    def refresh(self):
        with self._lock:
            time.sleep(1.0)
"""
        fs = lint(src, ["JX018"])
        assert rules_of(fs) == {"JX018"}
        assert fs[0].severity == "warning"
        assert "Registry._lock" in fs[0].message

    def test_http_through_callee_fires(self):
        # the blocking call is in a helper: only the closure sees it
        src = """
import threading
from urllib.request import urlopen

class Router:
    def __init__(self):
        self._lock = threading.Lock()

    def _fetch(self, url):
        return urlopen(url, timeout=2.0).read()

    def refresh(self, url):
        with self._lock:
            self._fetch(url)
"""
        fs = lint(src, ["JX018"])
        assert rules_of(fs) == {"JX018"}
        assert "network I/O" in fs[0].message

    def test_join_and_queue_get_under_lock_fire(self):
        src = """
import threading

class Pool:
    def __init__(self):
        self._lock = threading.Lock()

    def drain(self, worker, task_queue):
        with self._lock:
            worker.join()
            task_queue.get()
"""
        fs = lint(src, ["JX018"])
        cats = {f.message.split(" while holding")[0] for f in fs}
        assert cats == {"thread join", "queue wait"}

    def test_snapshot_then_work_outside_is_clean(self):
        # the fixed shape: snapshot under the lock, block outside it
        src = """
import threading
import time

class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}

    def refresh(self):
        with self._lock:
            items = dict(self._items)
        time.sleep(1.0)
        with self._lock:
            self._items.update(items)
"""
        assert lint(src, ["JX018"]) == []

    def test_wait_on_own_condition_is_exempt(self):
        # with self._cond: self._cond.wait() is the one legal block
        src = """
import threading

class Queue:
    def __init__(self):
        self._cond = threading.Condition()

    def pop(self):
        with self._cond:
            self._cond.wait()
"""
        assert lint(src, ["JX018"]) == []

    def test_unbounded_wait_on_foreign_event_fires(self):
        src = """
import threading

class Loader:
    def __init__(self):
        self._lock = threading.Lock()

    def block_on(self, event):
        with self._lock:
            event.wait()
"""
        fs = lint(src, ["JX018"])
        assert rules_of(fs) == {"JX018"}
        assert "blocking wait" in fs[0].message

    def test_named_lock_factory_is_discovered(self):
        # adopting the runtime tracer must not blind the static tier
        src = """
import time
from deeplearning4j_tpu.analysis.locktrace import named_lock

class Registry:
    def __init__(self):
        self._lock = named_lock("registry")

    def refresh(self):
        with self._lock:
            time.sleep(1.0)
"""
        fs = lint(src, ["JX018"])
        assert rules_of(fs) == {"JX018"}


class TestConcurrencyCLI:
    def test_graph_cli_reports_cycle_and_exits_nonzero(self, tmp_path):
        bad = tmp_path / "transfer.py"
        bad.write_text(ALL_RULES["JX017"].example)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-m", "deeplearning4j_tpu.analysis.concurrency",
             str(bad)],
            capture_output=True, text=True, env=env, timeout=300)
        assert proc.returncode == 1
        assert "cycles (JX017):" in proc.stdout

    def test_graph_cli_dot_output(self, tmp_path):
        bad = tmp_path / "transfer.py"
        bad.write_text(ALL_RULES["JX017"].example)
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-m", "deeplearning4j_tpu.analysis.concurrency",
             "--dot", str(bad)],
            capture_output=True, text=True, env=env, timeout=300)
        assert proc.stdout.startswith("digraph lock_order {")
        assert 'color="red"' in proc.stdout  # the cycle is highlighted

    def test_package_graph_is_cycle_free(self):
        """The repo's own lock-order graph must stay acyclic — the
        in-process twin of the JX017 tier-1 gate."""
        from deeplearning4j_tpu.analysis.concurrency import package_graph

        _edges, cycles, kinds = package_graph()
        assert kinds, "lock discovery found nothing — model regressed"
        assert cycles == [], f"lock-order cycles in the package: {cycles}"


class TestJX019UnfusedResidualTail:
    def _lint(self, src, path="deeplearning4j_tpu/nn/layers/fake_block.py"):
        return lint_source(src, path, rules=["JX019"])

    def test_residual_then_named_activation_fires(self):
        src = """
import jax

def forward(params, x, shortcut):
    y = jax.lax.conv_general_dilated(x, params["W"], (1, 1), "SAME")
    out = y + shortcut
    return jax.nn.relu(out)
"""
        fs = self._lint(src)
        assert rules_of(fs) == {"JX019"}
        assert "bottleneck_block" in fs[0].message

    def test_residual_through_resolved_activation_fires(self):
        src = """
import jax
from deeplearning4j_tpu.nn import activations

def forward(conf, params, x, shortcut):
    y = jax.lax.conv_general_dilated(x, params["W"], (1, 1), "SAME")
    act = activations.resolve(conf.activation)
    out = y + shortcut
    return act(out)
"""
        assert rules_of(self._lint(src)) == {"JX019"}

    def test_inline_residual_inside_activation_fires(self):
        src = """
import jax
from deeplearning4j_tpu.nn import activations

def forward(conf, params, x, shortcut):
    y = jax.lax.conv_general_dilated(x, params["W"], (1, 1), "SAME")
    return activations.resolve(conf.activation)(y + shortcut)
"""
        assert rules_of(self._lint(src)) == {"JX019"}

    def test_bias_add_epilogue_is_clean(self):
        # conv2d_apply's shape: the add's right operand names the param
        # leaf — XLA folds bias into the conv epilogue, nothing to fuse.
        src = """
import jax
from deeplearning4j_tpu.nn import activations

def forward(conf, params, x):
    out = jax.lax.conv_general_dilated(x, params["W"], (1, 1), "SAME")
    out = out + params["b"].astype(out.dtype)
    return activations.resolve(conf.activation)(out)
"""
        assert self._lint(src) == []

    def test_residual_without_conv_is_clean(self):
        # Transformer residuals around matmuls are a different traffic
        # story (the attention kernels own that fusion); the rule is
        # scoped to conv blocks.
        src = """
import jax

def forward(params, x, shortcut):
    y = x @ params["W"]
    out = y + shortcut
    return jax.nn.relu(out)
"""
        assert self._lint(src) == []

    def test_outside_layers_is_clean(self):
        src = """
import jax

def forward(params, x, shortcut):
    y = jax.lax.conv_general_dilated(x, params["W"], (1, 1), "SAME")
    out = y + shortcut
    return jax.nn.relu(out)
"""
        assert self._lint(src, path="deeplearning4j_tpu/models/fake.py") == []

    def test_package_is_clean(self):
        # nn/layers/ routes fused blocks through the bottleneck_block
        # kernel seam; no hand-stitched residual tails remain.
        assert [f for f in lint_package(rules=["JX019"])] == []


class TestJX020ShardingOutsideParallel:
    def _lint(self, src, path="deeplearning4j_tpu/serving/fake.py"):
        return lint_source(src, path, rules=["JX020"])

    def test_construction_outside_parallel_fires(self):
        src = """
from jax.sharding import NamedSharding, PartitionSpec

def place(mesh, tree):
    return NamedSharding(mesh, PartitionSpec(None, "model"))
"""
        fs = self._lint(src)
        assert rules_of(fs) == {"JX020"}
        # Import AND both constructor calls are each a finding.
        assert len(fs) == 3

    def test_attribute_construction_fires(self):
        src = """
import jax

def place(mesh):
    return jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
"""
        assert rules_of(self._lint(src)) == {"JX020"}

    def test_mesh_helpers_are_clean(self):
        # The sanctioned shape: ask parallel/mesh.py for the layout.
        src = """
from deeplearning4j_tpu.parallel import mesh as mesh_mod

def place(mesh, tree, context):
    reps = mesh_mod.replicated(mesh)
    pages = mesh_mod.kv_page_sharding(mesh, 4, context.model_axis)
    return reps, pages
"""
        assert self._lint(src) == []

    def test_inside_parallel_is_clean(self):
        src = """
from jax.sharding import NamedSharding, PartitionSpec as P

def axis_sharding(mesh, ndim, dim, axis):
    spec = [None] * ndim
    spec[dim] = axis
    return NamedSharding(mesh, P(*spec))
"""
        assert self._lint(
            src, path="deeplearning4j_tpu/parallel/mesh.py") == []

    def test_package_is_clean(self):
        # Every spec construction in the package lives in parallel/ (the
        # checkpoint restore-onto-mesh path asks mesh.replicated()).
        assert [f for f in lint_package(rules=["JX020"])] == []


class TestLinterFramework:
    def test_registry_has_all_rules(self):
        assert set(ALL_RULES) >= {"JX001", "JX002", "JX003", "JX004",
                                  "JX005", "JX006", "JX007", "JX008",
                                  "JX009", "JX010", "JX011", "JX012",
                                  "JX013", "JX014", "JX015", "JX016",
                                  "JX017", "JX018", "JX019", "JX020"}

    def test_every_rule_example_fires(self):
        """Each rule's --explain example must be a true positive for
        exactly that rule — the example IS the rule's spec."""
        for rid, cls in sorted(ALL_RULES.items()):
            assert cls.example, f"{rid} has no example"
            fs = lint_source(cls.example, cls.example_path, rules=[rid])
            assert rid in rules_of(fs), (
                f"{rid}'s own example does not fire it")

    def test_explain_cli_prints_docstring_and_example(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-m", "deeplearning4j_tpu.analysis",
             "--explain", "jx017"],
            capture_output=True, text=True, env=env, timeout=300)
        assert proc.returncode == 0
        assert proc.stdout.startswith("JX017")
        assert "Minimal true positive:" in proc.stdout
        assert "lock-order inversion" in proc.stdout

    def test_explain_cli_unknown_rule(self):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-m", "deeplearning4j_tpu.analysis",
             "--explain", "JX999"],
            capture_output=True, text=True, env=env, timeout=300)
        assert proc.returncode == 2
        assert "unknown rule" in proc.stderr

    def test_findings_are_typed_and_sorted(self):
        src = """
import jax

@jax.jit
def step(x):
    print(x)
    return x.item()
"""
        fs = lint(src)
        assert [f.rule for f in fs] == sorted(
            [f.rule for f in fs], key=lambda r: [x.rule for x in fs].index(r))
        for f in fs:
            assert f.path and f.line > 0 and f.message and f.severity in (
                "error", "warning") and f.context

    def test_disable_all_comment(self):
        src = """
import jax

@jax.jit
def step(x):
    return x.item()  # tpulint: disable=all
"""
        assert lint(src) == []

    def test_baseline_roundtrip(self, tmp_path):
        src = """
import jax

@jax.jit
def step(x):
    return x.item()
"""
        fs = lint(src, ["JX001"])
        bl = Baseline.from_findings(fs)
        p = tmp_path / "baseline.json"
        bl.save(str(p))
        loaded = Baseline.load(str(p))
        new, grandfathered, stale = loaded.split(fs)
        assert new == [] and len(grandfathered) == 1 and stale == []
        # freshly written entries carry TODO reasons -> must be rejected
        assert loaded.missing_reasons()


# ------------------------------------------------------------ tier-1 gate

class TestPackageGate:
    def test_package_lint_is_clean_against_baseline(self):
        """The in-process equivalent of the CLI gate (fast path)."""
        findings = lint_package()
        baseline = Baseline.load(DEFAULT_BASELINE_PATH)
        new, _grandfathered, _stale = baseline.split(findings)
        assert new == [], "new tpulint findings:\n" + "\n".join(
            f.format() for f in new)
        assert baseline.missing_reasons() == [], (
            "baselined findings without a reason: "
            f"{baseline.missing_reasons()}")

    def test_cli_over_package_exits_zero(self):
        """tier-1 registration: shell the CLI exactly as a developer would."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-m", "deeplearning4j_tpu.analysis"],
            capture_output=True, text=True, env=env, timeout=300)
        assert proc.returncode == 0, (
            f"tpulint CLI failed:\n{proc.stdout}\n{proc.stderr}")

    def test_cli_json_output_and_nonzero_on_violation(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(
            "import jax\n\n@jax.jit\ndef step(x):\n    return x.item()\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-m", "deeplearning4j_tpu.analysis",
             str(bad), "--no-baseline", "--json"],
            capture_output=True, text=True, env=env, timeout=300)
        assert proc.returncode == 1
        data = json.loads(proc.stdout)
        assert data["new"] and data["new"][0]["rule"] == "JX001"
