"""Regression tests for three fixed bugs.

1. `models/zoo._sample_tokens` (batched pick) dropped the `-inf` mask on
   excluded tokens, so temperature > 1 could re-admit tokens outside
   top-k via the re-inflated log(1e-12) floor.
2. uint8 network inputs were dtype-sniffed and divided by 255 even when
   the first layer is an ids-format EmbeddingLayer, silently zeroing the
   token ids. The policy now comes from the declared model structure.
3. `native/_fastvocab.so` was a committed binary; it must rebuild from
   `fastvocab.cpp` on first use.
"""

import os
import shutil

import numpy as np
import pytest

from deeplearning4j_tpu import (
    ComputationGraph,
    MultiLayerNetwork,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.models.zoo import _sample_token, _sample_tokens
from deeplearning4j_tpu.nn.conf.layers import (
    DenseLayer,
    EmbeddingLayer,
    OutputLayer,
)
from deeplearning4j_tpu.nn.conf.preprocessors import (
    UINT8_AMBIGUOUS,
    UINT8_IDS,
    UINT8_SCALE,
    resolve_uint8_policy,
)


# ------------------------------------------------- 1. batched sampling

class TestBatchedSamplingMask:
    def test_top_k_never_samples_excluded_tokens_at_high_temperature(self):
        # token 0 has ~all the mass but sits outside top_k once excluded;
        # temperature=20 flattens logits so a missing -inf mask would give
        # excluded tokens ~uniform odds — 200 draws would surely hit one.
        probs = np.tile(np.asarray([[0.90, 0.06, 0.04, 0.0, 0.0]]), (200, 1))
        rng = np.random.RandomState(7)
        ids = _sample_tokens(probs, rng, temperature=20.0, top_k=2)
        assert set(np.unique(ids)) <= {0, 1}

    def test_batched_matches_single_row_loop(self):
        rng = np.random.RandomState(3)
        probs = rng.dirichlet(np.ones(11), size=6)
        batched = _sample_tokens(probs, np.random.RandomState(42),
                                 temperature=1.7, top_k=4)
        loop_rng = np.random.RandomState(42)
        looped = [_sample_token(probs[i], loop_rng, temperature=1.7,
                                top_k=4, top_p=0.0)
                  for i in range(len(probs))]
        assert list(batched) == looped

    def test_greedy_path_unchanged(self):
        probs = np.asarray([[0.1, 0.7, 0.2], [0.5, 0.2, 0.3]])
        ids = _sample_tokens(probs, np.random.RandomState(0),
                             temperature=0.0, top_k=0)
        assert list(ids) == [1, 0]

    def test_single_top_p_does_not_mutate_input(self):
        probs = np.asarray([0.5, 0.3, 0.15, 0.05])
        before = probs.copy()
        _sample_token(probs, np.random.RandomState(0), temperature=1.0,
                      top_k=0, top_p=0.6)
        assert (probs == before).all()


# ------------------------------------------------- 2. uint8 input policy

class TestUint8Policy:
    def test_resolver(self):
        emb_ids = EmbeddingLayer(n_in=10, n_out=4, activation="identity")
        emb_onehot = EmbeddingLayer(n_in=10, n_out=4, activation="identity",
                                    input_format="onehot")
        dense = DenseLayer(n_in=10, n_out=4)
        assert resolve_uint8_policy([emb_ids]) == UINT8_IDS
        assert resolve_uint8_policy([dense]) == UINT8_SCALE
        assert resolve_uint8_policy([emb_onehot]) == UINT8_SCALE
        assert resolve_uint8_policy([emb_ids, dense]) == UINT8_AMBIGUOUS
        assert resolve_uint8_policy([]) == UINT8_SCALE

    def test_mln_embedding_uint8_ids_survive(self):
        conf = (NeuralNetConfiguration.builder().seed(5)
                .list()
                .layer(EmbeddingLayer(n_in=10, n_out=6,
                                      activation="identity"))
                .layer(OutputLayer(n_out=3, activation="softmax"))
                .build())
        net = MultiLayerNetwork(conf).init()
        ids = np.asarray([0, 3, 7, 9])
        out_u8 = np.asarray(net.output(ids.astype(np.uint8)))
        out_i32 = np.asarray(net.output(ids.astype(np.int32)))
        np.testing.assert_allclose(out_u8, out_i32, rtol=1e-6)
        # and distinct ids still give distinct rows (not all zeroed to id 0)
        assert not np.allclose(out_u8[0], out_u8[2])

    def test_mln_dense_uint8_still_scales(self):
        conf = (NeuralNetConfiguration.builder().seed(5)
                .list()
                .layer(DenseLayer(n_in=4, n_out=6, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax"))
                .build())
        net = MultiLayerNetwork(conf).init()
        x8 = np.asarray([[0, 51, 102, 255], [255, 0, 13, 26]], np.uint8)
        out_u8 = np.asarray(net.output(x8))
        out_f = np.asarray(net.output(x8.astype(np.float32) / 255.0))
        np.testing.assert_allclose(out_u8, out_f, rtol=1e-5)

    def test_graph_ambiguous_uint8_raises(self):
        gb = (NeuralNetConfiguration.builder().seed(5).graph_builder()
              .add_inputs("in")
              .add_layer("emb", EmbeddingLayer(n_in=10, n_out=4,
                                               activation="identity"), "in")
              .add_layer("dense", DenseLayer(n_in=1, n_out=4,
                                             activation="tanh"), "in")
              .add_layer("out", OutputLayer(n_in=4, n_out=2,
                                            activation="softmax"), "emb")
              .set_outputs("out"))
        net = ComputationGraph(gb.build()).init()
        ids = np.asarray([[1], [2]], np.uint8)
        with pytest.raises(ValueError, match="ambiguous"):
            net.output(ids)
        # non-uint8 input is unaffected by the ambiguity
        net.output(ids.astype(np.int32))

    def test_graph_embedding_only_uint8_is_ids(self):
        gb = (NeuralNetConfiguration.builder().seed(5).graph_builder()
              .add_inputs("in")
              .add_layer("emb", EmbeddingLayer(n_in=10, n_out=4,
                                               activation="identity"), "in")
              .add_layer("out", OutputLayer(n_in=4, n_out=2,
                                            activation="softmax"), "emb")
              .set_outputs("out"))
        net = ComputationGraph(gb.build()).init()
        ids = np.asarray([1, 4, 9])
        out_u8 = np.asarray(net.output(ids.astype(np.uint8))[0])
        out_i32 = np.asarray(net.output(ids.astype(np.int32))[0])
        np.testing.assert_allclose(out_u8, out_i32, rtol=1e-6)


# ------------------------------------------------- 3. fastvocab rebuild

class TestFastvocabRebuild:
    def test_so_is_not_tracked_and_rebuilds_from_source(self, tmp_path):
        from deeplearning4j_tpu import native as native_mod

        if shutil.which("g++") is None and shutil.which("c++") is None:
            pytest.skip("no C++ compiler available")
        so = os.path.join(os.path.dirname(native_mod.__file__),
                          "_fastvocab.so")
        moved = tmp_path / "_fastvocab.so"
        had_so = os.path.exists(so)
        if had_so:
            shutil.move(so, moved)
        native_mod._LIBS.pop("fastvocab", None)
        try:
            lib = native_mod._lib("fastvocab")
            assert lib is not None, "fastvocab failed to rebuild from source"
            assert os.path.exists(so)
        finally:
            native_mod._LIBS.pop("fastvocab", None)
            if had_so and not os.path.exists(so):
                shutil.move(moved, so)
