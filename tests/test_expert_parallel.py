"""Expert-parallel MoE tests (8-device virtual CPU mesh).

The reference predates MoE (SURVEY.md §2.3: its only parallelism is data
parallel); these cover the TPU-native extension — exact equivalence of the
GShard-style einsum MoE with and without expert sharding, against a
per-token reference, gradients, and training.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel import mesh as mesh_mod
from deeplearning4j_tpu.parallel.expert import (
    dense_moe_reference,
    init_moe_params,
    moe_ffn,
    shard_moe_params,
)


@pytest.fixture
def params():
    return init_moe_params(jax.random.PRNGKey(0), d_model=6, d_hidden=8,
                           n_experts=8, dtype=jnp.float64)


@pytest.fixture
def mesh():
    return mesh_mod.create_mesh((8,), axis_names=("expert",))


class TestMoE:
    def test_matches_per_token_reference(self, rng, params):
        x = jnp.asarray(rng.randn(32, 6))
        got = moe_ffn(params, x, capacity_factor=8.0)  # no dropping
        want = dense_moe_reference(params, x, capacity_factor=8.0)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-8, atol=1e-10)

    def test_capacity_drops_match_reference(self, rng, params):
        # Tight capacity: some tokens drop to zero, identically in both.
        x = jnp.asarray(rng.randn(64, 6))
        got = moe_ffn(params, x, capacity_factor=0.5)
        want = dense_moe_reference(params, x, capacity_factor=0.5)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-8, atol=1e-10)
        assert np.any(np.all(want == 0.0, axis=1))  # dropping happened

    def test_expert_sharding_is_exact(self, rng, params, mesh):
        x = jnp.asarray(rng.randn(40, 6))
        sharded = shard_moe_params(params, mesh)
        got = jax.jit(lambda p, x: moe_ffn(p, x, mesh=mesh))(sharded, x)
        want = moe_ffn(params, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-9, atol=1e-11)

    def test_trains_on_mesh(self, rng, params, mesh):
        x = jnp.asarray(rng.randn(32, 6))
        tgt = jnp.asarray(rng.randn(32, 6) * 0.1)
        p = shard_moe_params(params, mesh)

        @jax.jit
        def step(p):
            def loss(p):
                return jnp.mean((moe_ffn(p, x, mesh=mesh) - tgt) ** 2)
            l, g = jax.value_and_grad(loss)(p)
            return jax.tree.map(lambda a, ga: a - 0.3 * ga, p, g), l

        l0 = None
        for i in range(80):
            p, l = step(p)
            l = float(l)
            l0 = l if l0 is None else l0
        assert l < 0.6 * l0, (l0, l)
        # Router gradients flow (gate_w moved).
        assert not np.allclose(np.asarray(p["gate_w"]),
                               np.asarray(params["gate_w"]))
