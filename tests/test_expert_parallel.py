"""Expert-parallel MoE tests (8-device virtual CPU mesh).

The reference predates MoE (SURVEY.md §2.3: its only parallelism is data
parallel); these cover the TPU-native extension — exact equivalence of the
GShard-style einsum MoE with and without expert sharding, against a
per-token reference, gradients, and training.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel import mesh as mesh_mod
from deeplearning4j_tpu.parallel.expert import (
    dense_moe_reference,
    init_moe_params,
    moe_ffn,
    shard_moe_params,
)


@pytest.fixture
def params():
    return init_moe_params(jax.random.PRNGKey(0), d_model=6, d_hidden=8,
                           n_experts=8, dtype=jnp.float64)


@pytest.fixture
def mesh():
    return mesh_mod.create_mesh((8,), axis_names=("expert",))


class TestMoE:
    def test_matches_per_token_reference(self, rng, params):
        x = jnp.asarray(rng.randn(32, 6))
        got = moe_ffn(params, x, capacity_factor=8.0)  # no dropping
        want = dense_moe_reference(params, x, capacity_factor=8.0)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-8, atol=1e-10)

    def test_capacity_drops_match_reference(self, rng, params):
        # Tight capacity: some tokens drop to zero, identically in both.
        x = jnp.asarray(rng.randn(64, 6))
        got = moe_ffn(params, x, capacity_factor=0.5)
        want = dense_moe_reference(params, x, capacity_factor=0.5)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-8, atol=1e-10)
        assert np.any(np.all(want == 0.0, axis=1))  # dropping happened

    def test_expert_sharding_is_exact(self, rng, params, mesh):
        x = jnp.asarray(rng.randn(40, 6))
        sharded = shard_moe_params(params, mesh)
        got = jax.jit(lambda p, x: moe_ffn(p, x, mesh=mesh))(sharded, x)
        want = moe_ffn(params, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-9, atol=1e-11)

    def test_top2_matches_per_token_reference(self, rng, params):
        x = jnp.asarray(rng.randn(32, 6))
        got = moe_ffn(params, x, capacity_factor=8.0, top_k=2)
        want = dense_moe_reference(params, x, capacity_factor=8.0, top_k=2)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-8, atol=1e-10)

    def test_top2_capacity_queueing_matches_reference(self, rng, params):
        # Tight capacity: second choices queue behind ALL first choices
        # (GShard), identically in both implementations.
        x = jnp.asarray(rng.randn(64, 6))
        got = moe_ffn(params, x, capacity_factor=0.3, top_k=2)
        want = dense_moe_reference(params, x, capacity_factor=0.3, top_k=2)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-8, atol=1e-10)

    def test_top2_expert_sharding_is_exact(self, rng, params, mesh):
        x = jnp.asarray(rng.randn(40, 6))
        sharded = shard_moe_params(params, mesh)
        got = jax.jit(lambda p, x: moe_ffn(p, x, mesh=mesh, top_k=2))(
            sharded, x)
        want = moe_ffn(params, x, top_k=2)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-9, atol=1e-11)

    def test_top2_saturated_router_picks_distinct_expert(self, rng, params):
        # A saturated softmax zeroes the non-first-choice probs exactly; the
        # second choice must still be a DIFFERENT expert (highest remaining
        # logit), not a re-dispatch to the first (code-review regression).
        gw = np.zeros((6, 8))
        gw[:, 0] = 2000.0  # fp saturation: probs = [1, 0, ..., 0]
        gw[0, 1] = 1.0     # expert 1 is the runner-up on logits
        p = dict(params, gate_w=jnp.asarray(gw))
        x = jnp.asarray(np.abs(rng.randn(16, 6)))
        got = moe_ffn(p, x, capacity_factor=0.6, top_k=2)
        want = dense_moe_reference(p, x, capacity_factor=0.6, top_k=2)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-8, atol=1e-10)

    def test_aux_loss_uniform_router_is_one(self):
        # Round-robin gate_w routes each expert exactly N/E first-choice
        # tokens with near-uniform probs, so the GShard aux loss
        # E * sum(frac * mean_prob) ~= E * sum((1/E) * (1/E)) = 1.
        E, N, D = 4, 32, 4
        params = init_moe_params(jax.random.PRNGKey(1), d_model=D,
                                 d_hidden=4, n_experts=E, dtype=jnp.float64)
        # Route round-robin: gate_w = small identity-ish so token i prefers
        # expert i % E weakly.
        gw = np.zeros((D, E))
        for j in range(E):
            gw[j % D, j] = 0.01
        params = dict(params, gate_w=jnp.asarray(gw))
        x = np.zeros((N, D))
        for i in range(N):
            x[i, (i % E) % D] = 1.0
        # This x makes every expert argmax-win exactly N/E tokens only when
        # gw maps distinct input dims to distinct experts; with D==E it does.
        _, aux = moe_ffn(params, jnp.asarray(x), capacity_factor=8.0,
                         return_aux=True)
        assert abs(float(aux) - 1.0) < 0.05, float(aux)

    def test_aux_loss_penalizes_collapse(self, rng):
        # A router that sends every token to expert 0 must score aux close
        # to E * mean_prob_0 >> 1.
        E, D = 4, 6
        params = init_moe_params(jax.random.PRNGKey(2), d_model=D,
                                 d_hidden=4, n_experts=E, dtype=jnp.float64)
        gw = np.zeros((D, E))
        gw[:, 0] = 5.0  # strong preference for expert 0
        params = dict(params, gate_w=jnp.asarray(gw))
        x = jnp.asarray(np.abs(rng.randn(32, D)))
        _, aux = moe_ffn(params, x, return_aux=True)
        assert float(aux) > 1.5, float(aux)

    def test_aux_loss_balances_training(self, rng):
        # Train ONLY on the aux loss: expert assignment must spread out.
        E, D, N = 4, 6, 64
        params = init_moe_params(jax.random.PRNGKey(3), d_model=D,
                                 d_hidden=4, n_experts=E, dtype=jnp.float64)
        gw = np.zeros((D, E))
        gw[:, 0] = 2.0  # start collapsed
        p = dict(params, gate_w=jnp.asarray(gw))
        x = jnp.asarray(rng.randn(N, D))

        @jax.jit
        def step(p):
            def loss(p):
                return moe_ffn(p, x, return_aux=True)[1]
            l, g = jax.value_and_grad(loss)(p)
            return {k: p[k] - 0.5 * g[k] for k in p}, l

        for _ in range(60):
            p, aux = step(p)
        probs = jax.nn.softmax(x @ p["gate_w"], axis=-1)
        counts = np.bincount(np.asarray(jnp.argmax(probs, -1)), minlength=E)
        # Balanced enough: max expert load within 2x of the mean.
        assert counts.max() <= 2.0 * (N / E), counts

    def test_router_jitter_perturbs_and_eval_is_deterministic(self, rng,
                                                              params):
        x = jnp.asarray(rng.randn(32, 6))
        base = moe_ffn(params, x)
        jit1 = moe_ffn(params, x, rng=jax.random.PRNGKey(7), jitter_eps=0.5)
        jit2 = moe_ffn(params, x, rng=jax.random.PRNGKey(8), jitter_eps=0.5)
        # Large jitter changes at least some routing decisions...
        assert not np.allclose(np.asarray(jit1), np.asarray(jit2))
        # ...and rng=None (eval) is bit-deterministic.
        np.testing.assert_array_equal(np.asarray(base),
                                      np.asarray(moe_ffn(params, x)))

    def test_trains_on_mesh(self, rng, params, mesh):
        x = jnp.asarray(rng.randn(32, 6))
        tgt = jnp.asarray(rng.randn(32, 6) * 0.1)
        p = shard_moe_params(params, mesh)

        @jax.jit
        def step(p):
            def loss(p):
                return jnp.mean((moe_ffn(p, x, mesh=mesh) - tgt) ** 2)
            l, g = jax.value_and_grad(loss)(p)
            return jax.tree.map(lambda a, ga: a - 0.3 * ga, p, g), l

        l0 = None
        for i in range(80):
            p, l = step(p)
            l = float(l)
            l0 = l if l0 is None else l0
        assert l < 0.6 * l0, (l0, l)
        # Router gradients flow (gate_w moved).
        assert not np.allclose(np.asarray(p["gate_w"]),
                               np.asarray(params["gate_w"]))
