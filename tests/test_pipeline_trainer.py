"""Engine-driven pipeline parallelism: a DSL-built MultiLayerNetwork with
structurally-repeated blocks trains through `PipelineTrainer`'s GPipe
schedule and matches unpipelined training parameter-for-parameter.

No reference equivalent (SURVEY.md §2.3 TPU-native extension row); the
equivalence contract mirrors the reference's distributed-vs-single-machine
tests (`TestCompareParameterAveragingSparkVsSingleMachine`).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import mesh as mesh_mod
from deeplearning4j_tpu.parallel.pipeline_trainer import PipelineTrainer


def _conf(n_blocks=4, width=16, updater="sgd"):
    b = (NeuralNetConfiguration.builder()
         .seed(44).learning_rate(0.05).updater(updater)
         .list()
         .layer(DenseLayer(n_out=width, activation="tanh")))
    for _ in range(n_blocks * 2):
        b = b.layer(DenseLayer(n_out=width, activation="tanh"))
    return (b.layer(OutputLayer(n_out=3, activation="softmax",
                                loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(6))
            .build())


def _data(rng, n=8):
    X = rng.randn(n, 6).astype("float32")
    Y = np.eye(3)[rng.randint(0, 3, n)].astype("float32")
    return X, Y


@pytest.mark.parametrize("updater", ["sgd", "adam"])
def test_pipelined_training_matches_plain(rng, updater):
    X, Y = _data(rng)
    net0 = MultiLayerNetwork(_conf(updater=updater)).init()
    for _ in range(4):
        net0.fit(DataSet(X, Y))

    net1 = MultiLayerNetwork(_conf(updater=updater)).init()
    mesh = mesh_mod.create_mesh((2, 4), axis_names=("data", "pipe"))
    pt = PipelineTrainer(net1, mesh, block_range=(1, 9), n_microbatches=2)
    pt.fit((X, Y))  # (x, y) convenience form, like the engine's fit
    for _ in range(3):
        pt.fit(DataSet(X, Y))

    assert abs(net0.score_value - net1.score_value) < 1e-4
    for lk in net0.params_tree:
        for pk in net0.params_tree[lk]:
            np.testing.assert_allclose(
                np.asarray(net0.params_tree[lk][pk]),
                np.asarray(net1.params_tree[lk][pk]),
                rtol=2e-4, atol=2e-5, err_msg=f"{lk}/{pk}")


def test_structural_validation(rng):
    """Mismatched stages and in-range dropout are rejected at construction."""
    mesh = mesh_mod.create_mesh((2, 4), axis_names=("data", "pipe"))
    conf = (NeuralNetConfiguration.builder()
            .seed(1).learning_rate(0.1).updater("sgd")
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(DenseLayer(n_out=12, activation="tanh"))  # width breaks
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(6))
            .build())
    net = MultiLayerNetwork(conf).init()
    with pytest.raises(ValueError, match="differs structurally"):
        PipelineTrainer(net, mesh, block_range=(1, 5), n_microbatches=2)

    conf2 = _conf()
    for i in range(1, 9):  # uniform, so the structural check passes
        conf2.layers[i].dropout = 0.5
    net2 = MultiLayerNetwork(conf2).init()
    with pytest.raises(ValueError, match="dropout"):
        PipelineTrainer(net2, mesh, block_range=(1, 9), n_microbatches=2)

    # Same shapes, different activation: must be rejected (the block body
    # applies stage 0's config to every stage).
    conf3 = _conf()
    conf3.layers[5].activation = "relu"
    net3 = MultiLayerNetwork(conf3).init()
    with pytest.raises(ValueError, match="differs structurally"):
        PipelineTrainer(net3, mesh, block_range=(1, 9), n_microbatches=2)

    with pytest.raises(ValueError, match="multiple of the pipe"):
        PipelineTrainer(MultiLayerNetwork(_conf()).init(), mesh,
                        block_range=(1, 8), n_microbatches=2)


def test_remat_pipelined_training_matches_plain(rng):
    """remat=True (jax.checkpoint around the stage body) changes memory,
    never numerics."""
    X, Y = _data(rng)
    net0 = MultiLayerNetwork(_conf()).init()
    for _ in range(3):
        net0.fit(DataSet(X, Y))

    net1 = MultiLayerNetwork(_conf()).init()
    mesh = mesh_mod.create_mesh((2, 4), axis_names=("data", "pipe"))
    pt = PipelineTrainer(net1, mesh, block_range=(1, 9), n_microbatches=2,
                         remat=True)
    for _ in range(3):
        pt.fit(DataSet(X, Y))
    for lk in net0.params_tree:
        for pk in net0.params_tree[lk]:
            np.testing.assert_allclose(
                np.asarray(net0.params_tree[lk][pk]),
                np.asarray(net1.params_tree[lk][pk]),
                rtol=2e-4, atol=2e-5, err_msg=f"{lk}/{pk}")
