"""Fault-tolerant serving fleet (`serving/fleet.py` + `serving/router.py`).

Acceptance coverage for the fleet PR:

- `util/retry.Backoff` honors a total elapsed-time budget
  (`max_elapsed_s`) and `RetryError` reports what the envelope cost;
- the coordinator's `status` op exposes per-member role + lease age, the
  client parses it, and the CLI renders it;
- `ModelHost._reload` holds the host lock only around bookkeeping: while
  one model loads, snapshots and OTHER models proceed, and the reloading
  model 503s instead of queueing callers behind the load;
- the router picks the least-loaded live replica, fails over under the
  request's deadline budget with classified retries (503/refused always,
  after-admission only when idempotent — a partial generation is never
  blind-retried), sheds with a 503 counted distinctly from failures;
- a 3-replica fleet under a deterministic fault plan (one replica
  SIGKILLed mid-request, one hung mid-decode) sustains >= 99%
  availability with sub-second failover;
- a rolling model update drains each replica, AOT-warms the new
  checkpoint while drained, and re-admits it with ZERO client-visible
  errors and ZERO serving-path compiles after rejoin;
- SIGTERM is a graceful drain: exit code 0, clean leave, never counted
  dead;
- the fleet SLO families all land in ONE `/metrics` scrape.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from deeplearning4j_tpu import (MultiLayerNetwork, NeuralNetConfiguration,
                                observability as obs)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel.coordinator import (
    Coordinator,
    CoordinatorClient,
)
from deeplearning4j_tpu.serving import (
    Autoscaler,
    FleetManager,
    FleetRouter,
    ModelNotReadyError,
    ReplicaDrainingError,
    ReplicaServer,
    ServerOverloadedError,
)
from deeplearning4j_tpu.serving.host import ModelHost
from deeplearning4j_tpu.serving.router import (
    PartialFailureError,
    ReplicaInfo,
    UpstreamError,
    sum_metric_families,
)
from deeplearning4j_tpu.util.faultinject import FaultPlan
from deeplearning4j_tpu.util.retry import Backoff, RetryError, with_retries


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def mlp_net(seed=1, n_in=3, n_out=2):
    return MultiLayerNetwork(
        (NeuralNetConfiguration.builder()
         .seed(seed).learning_rate(0.1).weight_init("xavier")
         .list()
         .layer(DenseLayer(n_out=4, activation="tanh"))
         .layer(OutputLayer(n_out=n_out, activation="softmax",
                            loss_function="mcxent"))
         .set_input_type(InputType.feed_forward(n_in))
         .build())).init()


def _save(net, path):
    from deeplearning4j_tpu.checkpoint.manager import CheckpointManager

    CheckpointManager(str(path), async_save=False).save(net)
    return str(path)


def _sub_env(plan=None):
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    if plan is not None:
        env["DL4J_TPU_FAULT_PLAN"] = json.dumps(plan)
    return env


def _wait(predicate, timeout_s, every_s=0.1, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(every_s)
    raise AssertionError(f"timed out after {timeout_s}s waiting for {what}")


# ---------------------------------------------------- satellite 1: retry


class TestBackoffElapsedBudget:
    def test_budget_stops_before_an_overshooting_sleep(self):
        # base 5s sleep would blow a 0.2s budget: the envelope must give
        # up BEFORE sleeping, not after.
        calls = []

        def fail():
            calls.append(1)
            raise OSError("down")

        bo = Backoff(base_s=5.0, max_s=5.0, tries=10, jitter=False,
                     max_elapsed_s=0.2)
        t0 = time.monotonic()
        with pytest.raises(RetryError) as ei:
            bo.run(fail, retry_on=(OSError,), describe="join")
        assert time.monotonic() - t0 < 1.0
        assert len(calls) == 1
        assert ei.value.attempts == 1
        assert ei.value.elapsed < 1.0
        assert isinstance(ei.value.last, OSError)

    def test_budget_allows_retries_that_fit(self):
        calls = []

        def fail():
            calls.append(1)
            raise OSError("down")

        bo = Backoff(base_s=0.01, max_s=0.01, tries=3, jitter=False,
                     max_elapsed_s=5.0)
        with pytest.raises(RetryError) as ei:
            bo.run(fail, retry_on=(OSError,))
        assert len(calls) == 3
        assert ei.value.attempts == 3

    def test_tighter_of_deadline_and_max_elapsed_wins(self):
        assert Backoff(deadline_s=5.0, max_elapsed_s=0.1)._budget() == 0.1
        assert Backoff(deadline_s=0.1, max_elapsed_s=5.0)._budget() == 0.1
        assert Backoff(max_elapsed_s=2.0)._budget() == 2.0
        assert Backoff()._budget() is None

    def test_with_retries_forwards_max_elapsed(self):
        t0 = time.monotonic()
        with pytest.raises(RetryError):
            with_retries(lambda: (_ for _ in ()).throw(OSError("x")),
                         tries=50, base_s=1.0, max_elapsed_s=0.05,
                         retry_on=(OSError,))
        assert time.monotonic() - t0 < 1.0


# --------------------------------------- satellite 2: coordinator status


class TestCoordinatorStatusDetail:
    def test_status_carries_role_and_lease_age(self):
        coord = Coordinator(lost_after_s=30.0).start()
        addr = coord.address
        try:
            rep = CoordinatorClient(addr, "r0@127.0.0.1:9999",
                                    role="replica:warming")
            rep.join(role="replica:warming")
            trainer = CoordinatorClient(addr, "t0")
            trainer.join()
            doc = trainer.status()
            assert doc["lost_after_s"] == 30.0
            d = doc["detail"]
            assert d["r0@127.0.0.1:9999"]["role"] == "replica:warming"
            assert d["t0"]["role"] == "trainer"
            for row in d.values():
                assert 0.0 <= row["lease_age_s"] < 30.0
            # Re-join with a new role updates in place (the replica
            # lifecycle: warming -> routable -> draining).
            rep.join(role="replica")
            assert trainer.status()["detail"][
                "r0@127.0.0.1:9999"]["role"] == "replica"
        finally:
            coord.close()

    def test_cli_renders_membership(self, capsys):
        from deeplearning4j_tpu.parallel import coordinator as coordmod

        coord = Coordinator(lost_after_s=15.0).start()
        addr = coord.address
        try:
            c = CoordinatorClient(addr, "rep@127.0.0.1:1234", role="replica")
            c.join(role="replica")
            rc = coordmod.main([addr, "--timeout-s", "2.0"])
        finally:
            coord.close()
        assert rc == 0
        out = capsys.readouterr().out
        assert "rep@127.0.0.1:1234" in out
        assert "role=replica" in out
        assert "lease_age=" in out


# ------------------------------------- satellite 3: narrow reload locking


class TestHostReloadNarrowLock:
    def test_slow_reload_blocks_only_its_own_model(self, tmp_path,
                                                   monkeypatch):
        from deeplearning4j_tpu.checkpoint import legacy as _legacy

        pa = _save(mlp_net(seed=1), tmp_path / "a")
        pb = _save(mlp_net(seed=2), tmp_path / "b")
        # The server's on_load attaches the batcher then flips ready; the
        # bare-host stand-in just flips ready.
        host = ModelHost(on_load=lambda m: m.ready.set())
        host.add("a", path=pa)
        host.add("b", path=pb)
        host.get("a")
        host.get("b")  # both resident
        with host._lock:
            host._evict(host._models["a"])

        started, release = threading.Event(), threading.Event()
        real_load = _legacy.load_any

        def slow_load(path, *a, **kw):
            started.set()
            assert release.wait(10.0)
            return real_load(path, *a, **kw)

        monkeypatch.setattr(_legacy, "load_any", slow_load)
        errors = []

        def reload_a():
            try:
                host.get("a")
            except Exception as e:  # pragma: no cover - failure detail
                errors.append(e)

        loader = threading.Thread(target=reload_a, daemon=True)
        loader.start()
        assert started.wait(5.0)
        try:
            # While the load is in flight, the host lock must be free:
            # snapshots and the OTHER model answer immediately.
            done = {}

            def probe():
                done["snapshot"] = {r["name"]: r["status"]
                                    for r in host.snapshot()}
                done["b"] = host.get("b").name

            p = threading.Thread(target=probe, daemon=True)
            p.start()
            p.join(2.0)
            assert not p.is_alive(), \
                "snapshot()/get('b') blocked behind model a's reload"
            assert done["snapshot"]["a"] == "loading"
            assert done["b"] == "b"
            # Concurrent callers of the SAME model get a retryable 503,
            # not a queue position behind the load.
            with pytest.raises(ModelNotReadyError):
                host.get("a")
        finally:
            release.set()
            loader.join(10.0)
        assert not errors
        assert host.get("a").resident
        assert {r["name"]: r["status"]
                for r in host.snapshot()}["a"] == "ready"

    def test_failed_publish_clears_loading_and_recovers(self, tmp_path):
        # If the post-load publish (on_load hook, budget enforcement)
        # raises, the model must roll back to the evicted state — NOT
        # stay loading=True forever, which would 503 every future get()
        # with no recovery path.
        pa = _save(mlp_net(seed=1), tmp_path / "a")
        boom = [True]

        def on_load(m):
            if boom[0]:
                boom[0] = False
                raise RuntimeError("hook exploded")
            m.ready.set()

        host = ModelHost(on_load=on_load)
        host.add("a", path=pa)
        with pytest.raises(RuntimeError):
            host.get("a")
        m = host._models["a"]
        assert m.loading is False
        assert not m.resident  # rolled back, not half-published
        # The next caller retries the load and succeeds.
        assert host.get("a").resident
        assert m.ready.is_set()


# ------------------------------------------------------ router unit tests


def _fake_replica(behavior, load=0.0):
    """A stub replica: `behavior(path) -> (code, payload)` for POSTs,
    /metrics exposes `load` as queue depth."""

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _send(self, code, obj):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            code, obj = behavior(self.path)
            self._send(code, obj)

        def do_GET(self):
            text = ('dl4j_serving_model_queue_depth'
                    '{model="default",route="predict"} %s\n' % load)
            body = text.encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def _info(name, port, load=0.0, state="live"):
    return ReplicaInfo(
        worker_id=f"{name}@127.0.0.1:{port}", name=name,
        url=f"http://127.0.0.1:{port}", state=state, lease_age_s=0.0,
        seen_at=time.monotonic(), load=load)


def _router_with(infos, **kw):
    kw.setdefault("failover_tries", 4)
    kw.setdefault("request_timeout_s", 10.0)
    r = FleetRouter("127.0.0.1:1", http=False, **kw)
    r._table = {i.worker_id: i for i in infos}
    return r


class TestRouterRouting:
    def test_pick_least_loaded_live_only(self):
        r = _router_with([_info("a", 1, load=5.0), _info("b", 2, load=1.0),
                          _info("c", 3, load=0.0, state="warming"),
                          _info("d", 4, load=0.0, state="draining")])
        assert r._pick(exclude=set()).name == "b"

    def test_pick_skips_quarantined_and_stale_leases(self):
        a, b = _info("a", 1, load=0.0), _info("b", 2, load=9.0)
        r = _router_with([a, b])
        r._quarantine[a.worker_id] = time.monotonic() + 60.0
        assert r._pick(exclude=set()).name == "b"
        b.lease_age_s = 100.0  # most of the way past lost_after
        assert r._pick(exclude=set()) is None

    def test_equal_load_round_robins(self):
        r = _router_with([_info("a", 1), _info("b", 2), _info("c", 3)])
        picked = {r._pick(exclude=set()).name for _ in range(6)}
        assert picked == {"a", "b", "c"}

    def test_empty_fleet_sheds_distinctly(self):
        r = _router_with([])
        with pytest.raises(ServerOverloadedError):
            r.predict([[1.0, 2.0, 3.0]])
        assert r.counts()["shed"] == 1
        assert r.counts()["failed"] == 0

    def test_all_replicas_503_sheds(self):
        busy = _fake_replica(lambda p: (503, {"error": "queue full"}))
        try:
            r = _router_with([_info("a", busy.server_address[1])])
            with pytest.raises(ServerOverloadedError):
                r.predict([[1.0, 2.0, 3.0]])
            assert r.counts()["shed"] == 1
        finally:
            busy.shutdown()

    def test_predict_fails_over_on_5xx_and_observes_latency(self):
        bad = _fake_replica(lambda p: (500, {"error": "boom"}))
        ok = _fake_replica(lambda p: (200, {"predictions": [[0.5, 0.5]]}))
        fam = obs.metrics.get_family("dl4j_router_failover_seconds")
        before = fam.children()[0].histogram_state()[3] if fam.children() \
            else 0
        try:
            # bad has the lower load, so it is picked first.
            r = _router_with([
                _info("bad", bad.server_address[1], load=0.0),
                _info("ok", ok.server_address[1], load=5.0)])
            out = r.predict([[1.0, 2.0, 3.0]])
            assert out.shape == (1, 2)
            assert r.counts()["failover"] == 1
            assert r.counts()["ok"] == 0
            _, _, _, count = fam.children()[0].histogram_state()
            assert count == before + 1
        finally:
            bad.shutdown()
            ok.shutdown()

    def test_generate_never_retried_after_admission(self):
        bad = _fake_replica(lambda p: (500, {"error": "boom"}))
        ok_calls = []

        def ok_behavior(path):
            ok_calls.append(path)
            return 200, {"ids": [1, 2]}

        ok = _fake_replica(ok_behavior)
        try:
            r = _router_with([
                _info("bad", bad.server_address[1], load=0.0),
                _info("ok", ok.server_address[1], load=5.0)])
            with pytest.raises(PartialFailureError):
                r.generate([1, 2], 2)
            assert ok_calls == []  # the partial generation was NOT replayed
            assert r.counts()["failed"] == 1
        finally:
            bad.shutdown()
            ok.shutdown()

    def test_generate_fails_over_on_503_and_refused(self):
        # 503 = never admitted; refused = never reached a socket. Both are
        # safe for non-idempotent work.
        draining = _fake_replica(lambda p: (503, {"error": "draining"}))
        ok = _fake_replica(lambda p: (200, {"ids": [7, 8, 9]}))
        dead_port = _free_port()
        try:
            r = _router_with([
                _info("dead", dead_port, load=0.0),
                _info("drain", draining.server_address[1], load=1.0),
                _info("ok", ok.server_address[1], load=5.0)])
            assert r.generate([1], 3) == [7, 8, 9]
            assert r.counts()["failover"] == 1
        finally:
            draining.shutdown()
            ok.shutdown()

    def test_4xx_passes_through_without_failover(self):
        bad_req = _fake_replica(lambda p: (400, {"error": "bad dtype"}))
        ok = _fake_replica(lambda p: (200, {"predictions": [[1.0]]}))
        try:
            r = _router_with([
                _info("a", bad_req.server_address[1], load=0.0),
                _info("ok", ok.server_address[1], load=5.0)])
            with pytest.raises(UpstreamError) as ei:
                r.predict([[1.0]])
            assert ei.value.status == 400
            assert ei.value.payload() == {"error": "bad dtype"}
        finally:
            bad_req.shutdown()
            ok.shutdown()

    def test_failover_respects_deadline_budget(self):
        # Every replica down: the envelope must give up within the
        # caller's budget, surfacing RetryError with the spent budget.
        r = _router_with([_info("a", _free_port()),
                          _info("b", _free_port())],
                         failover_tries=50)
        t0 = time.monotonic()
        with pytest.raises((RetryError, ServerOverloadedError)):
            r.predict([[1.0, 2.0, 3.0]], timeout_s=1.0)
        assert time.monotonic() - t0 < 3.0

    def test_inflight_survives_table_rebuild(self):
        # A request in flight across a poll rebuild must decrement the
        # SAME counter it incremented: the per-replica score must return
        # to zero when the request finishes, not leak forever and skew
        # _pick away from the replica.
        entered, release = threading.Event(), threading.Event()

        def behavior(path):
            entered.set()
            assert release.wait(10.0)
            return 200, {"predictions": [[0.5, 0.5]]}

        srv = _fake_replica(behavior)
        try:
            port = srv.server_address[1]
            wid = f"a@127.0.0.1:{port}"
            r = _router_with([_info("a", port)])
            t = threading.Thread(
                target=lambda: r.predict([[1.0, 2.0, 3.0]]), daemon=True)
            t.start()
            assert entered.wait(5.0)
            with r._lock:
                assert r._inflight[wid] == 1
                # Simulate the poll loop rebuilding the table with FRESH
                # ReplicaInfo snapshots while the request is in flight.
                r._table = {i.worker_id: i for i in [_info("a", port)]}
            release.set()
            t.join(10.0)
            assert not t.is_alive()
            with r._lock:
                assert r._inflight.get(wid, 0) == 0
        finally:
            srv.shutdown()

    def test_shed_path_refresh_is_single_flight(self):
        # Concurrent about-to-shed requests must share ONE membership
        # refresh instead of each dogpiling the coordinator.
        r = _router_with([])
        calls = []
        gate = threading.Event()

        def fake_refresh():
            calls.append(1)
            assert gate.wait(5.0)
            return []

        r._refresh_membership = fake_refresh
        threads = [threading.Thread(target=r._refresh_membership_shared,
                                    daemon=True) for _ in range(5)]
        for t in threads:
            t.start()
        time.sleep(0.2)  # let the rest pile up on the single-flight lock
        gate.set()
        for t in threads:
            t.join(5.0)
        assert len(calls) == 1


# ------------------------------------------------- replica fault seam


class TestReplicaFaultSeam:
    def test_fleet_fault_kinds_parse(self):
        plan = FaultPlan.from_json(json.dumps([
            {"kind": "kill_replica", "step": 10, "worker": 0},
            {"kind": "hang_replica", "step": 3, "worker": 1,
             "seconds": 2.0, "stop_heartbeats": True},
            {"kind": "slow_decode", "step": 5, "worker": 2, "ms": 50},
        ]))
        assert [f.kind for f in plan.faults] == [
            "kill_replica", "hang_replica", "slow_decode"]
        assert plan.faults[1].args["stop_heartbeats"] is True

    def test_slow_decode_and_drain_refusal(self):
        plan = FaultPlan.from_json(
            '[{"kind": "slow_decode", "step": 1, "worker": 0, "ms": 1}]')
        rep = ReplicaServer("127.0.0.1:1", net=mlp_net(), fault_plan=plan,
                            handle_sigterm=False)
        try:
            rep.on_request("predict")  # request 0: no fault
            rep.request_done()
            assert rep._slow_ms == 0.0
            rep.on_request("predict")  # request 1: fires, sticky latency
            rep.request_done()
            assert rep._slow_ms == 1.0
            assert plan.faults[0].fired
            rep._draining.set()
            with pytest.raises(ReplicaDrainingError):
                rep.on_request("predict")
            assert rep.inflight() == 0
        finally:
            rep.server.stop()


# ------------------------------------------- reload failure + termination


class TestReplicaReloadFailure:
    def _replica(self, tmp_path):
        pa = _save(mlp_net(seed=1), tmp_path / "ckpt_a")
        return pa, ReplicaServer("127.0.0.1:1", path=pa, warm=False,
                                 handle_sigterm=False)

    def test_bad_checkpoint_restores_old_model_and_rejoins(self, tmp_path):
        # A failed deploy must NOT leave the replica drained forever: the
        # old checkpoint comes back and the replica rejoins the fleet.
        pa, rep = self._replica(tmp_path)
        try:
            out = rep.reload(str(tmp_path / "nope"), warm=False)
            assert out["ok"] is False
            assert out["restored"] is True
            assert not rep._draining.is_set()  # back in rotation
            preds = rep.server.predict([[0.1, 0.2, 0.3]])
            assert preds.shape == (1, 2)  # old model still answers
            # A good checkpoint afterwards still deploys.
            pb = _save(mlp_net(seed=7), tmp_path / "ckpt_b")
            assert rep.reload(pb, warm=False)["ok"] is True
        finally:
            rep.server.stop()

    def test_sigterm_during_reload_defers_then_completes_drain(
            self, tmp_path):
        # SIGTERM while a rolling update owns the drained state must not
        # be dropped: the reload finishes, then performs the real drain
        # instead of rejoining — the process still exits gracefully.
        pa, rep = self._replica(tmp_path)
        pb = _save(mlp_net(seed=2), tmp_path / "ckpt_b")
        host = rep.server.models
        entered, release = threading.Event(), threading.Event()
        real_reload = host._reload

        def slow_reload(model):
            entered.set()
            assert release.wait(10.0)
            return real_reload(model)

        host._reload = slow_reload
        out = {}
        t = threading.Thread(
            target=lambda: out.update(rep.reload(pb, warm=False)),
            daemon=True)
        t.start()
        assert entered.wait(5.0)
        rep.drain(timeout_s=1.0)  # SIGTERM mid-update: deferred
        assert not rep._stopped.is_set()
        release.set()
        t.join(15.0)
        assert not t.is_alive()
        assert out["ok"] is True
        assert rep._stopped.is_set()  # the reload completed the drain
        # A terminating replica refuses further reloads.
        with pytest.raises(ReplicaDrainingError):
            rep.reload(pb, warm=False)


class _StaticTableRouter:
    def __init__(self, rows):
        self.rows = rows

    def table(self):
        return self.rows


class TestRollingUpdateAbort:
    def _rows(self, *servers):
        return [{"name": f"r{i}", "state": "live",
                 "url": f"http://127.0.0.1:{s.server_address[1]}"}
                for i, s in enumerate(servers)]

    def test_aborts_when_a_replica_reports_failed_swap(self, tmp_path):
        calls = []
        bad = _fake_replica(lambda p: (calls.append("bad") or 200,
                                       {"ok": False, "error": "bad ckpt",
                                        "restored": True}))
        good = _fake_replica(lambda p: (calls.append("good") or 200,
                                        {"ok": True}))
        try:
            router = _StaticTableRouter(self._rows(bad, good))
            mgr = FleetManager("127.0.0.1:1", str(tmp_path / "old"))
            results = mgr.rolling_update(str(tmp_path / "new"), router,
                                         timeout_s=5.0)
            assert results["r0"]["ok"] is False
            assert "r1" not in results  # rollout stopped at the failure
            assert calls == ["bad"]
        finally:
            bad.shutdown()
            good.shutdown()

    def test_aborts_on_http_error_instead_of_swallowing_it(self, tmp_path):
        # HTTPError subclasses OSError: a 500 from a failed reload must
        # abort the rollout, not be mistaken for a dead replica and
        # walked past onto the next one.
        calls = []
        bad = _fake_replica(lambda p: (calls.append("bad") or 500,
                                       {"error": "reload blew up"}))
        good = _fake_replica(lambda p: (calls.append("good") or 200,
                                        {"ok": True}))
        try:
            router = _StaticTableRouter(self._rows(bad, good))
            mgr = FleetManager("127.0.0.1:1", str(tmp_path / "old"))
            results = mgr.rolling_update(str(tmp_path / "new"), router,
                                         timeout_s=5.0)
            assert results["r0"] == {"ok": False, "error": "HTTP 500"}
            assert "r1" not in results
            assert calls == ["bad"]
        finally:
            bad.shutdown()
            good.shutdown()

    def test_connection_failure_skips_the_dead_replica(self, tmp_path):
        # A replica that died between the table snapshot and its turn is
        # skipped (the router evicts it on its own); the rollout carries
        # on to the survivors.
        good = _fake_replica(lambda p: (200, {"ok": True}))
        try:
            dead_row = {"name": "r0", "state": "live",
                        "url": f"http://127.0.0.1:{_free_port()}"}
            rows = [dead_row] + [
                {"name": "r1", "state": "live",
                 "url": f"http://127.0.0.1:{good.server_address[1]}"}]
            router = _StaticTableRouter(rows)
            mgr = FleetManager("127.0.0.1:1", str(tmp_path / "old"))
            results = mgr.rolling_update(str(tmp_path / "new"), router,
                                         timeout_s=5.0)
            assert results["r0"]["ok"] is False
            assert results["r1"]["ok"] is True
        finally:
            good.shutdown()


# ----------------------------------------------------------- autoscaler


class _StubRouter:
    def __init__(self):
        self.stats = {"live": 2, "total_load": 0.0, "p99_s": None}

    def load_stats(self):
        return dict(self.stats)


class TestAutoscaler:
    def _scaler(self, router, **kw):
        clock = [0.0]
        events = []
        kw.setdefault("min_replicas", 1)
        kw.setdefault("max_replicas", 4)
        kw.setdefault("queue_high", 8.0)
        kw.setdefault("queue_low", 1.0)
        kw.setdefault("breach_s", 5.0)
        kw.setdefault("cooldown_s", 10.0)
        sc = Autoscaler(router, spawn=lambda: events.append("spawn"),
                        retire=lambda: events.append("retire"),
                        _clock=lambda: clock[0], **kw)
        return sc, clock, events

    def test_sustained_breach_scales_up_once_then_cools_down(self):
        router = _StubRouter()
        router.stats.update(live=2, total_load=40.0)  # 20 per replica
        sc, clock, events = self._scaler(router)
        sc.evaluate()  # breach noted, not yet sustained
        assert events == []
        clock[0] = 6.0
        assert sc.evaluate() == "up"
        assert events == ["spawn"]
        clock[0] = 12.0  # breach again but inside cooldown
        sc.evaluate()
        clock[0] = 14.0
        sc.evaluate()
        assert events == ["spawn"]
        clock[0] = 30.0  # cooldown over, breach must re-sustain
        sc.evaluate()
        clock[0] = 36.0
        assert sc.evaluate() == "up"
        assert events == ["spawn", "spawn"]

    def test_transient_spike_never_scales(self):
        router = _StubRouter()
        sc, clock, events = self._scaler(router)
        router.stats.update(total_load=40.0)
        sc.evaluate()
        router.stats.update(total_load=0.0)  # spike gone — and idle is
        clock[0] = 6.0                       # also not yet sustained
        sc.evaluate()
        assert events == []

    def test_p99_slo_breach_scales_up(self):
        router = _StubRouter()
        router.stats.update(p99_s=2.0)
        sc, clock, events = self._scaler(router, p99_slo_s=0.5)
        sc.evaluate()
        clock[0] = 6.0
        assert sc.evaluate() == "up"

    def test_sustained_idle_scales_down_to_min(self):
        router = _StubRouter()
        router.stats.update(live=3, total_load=0.0)
        sc, clock, events = self._scaler(router)
        sc.evaluate()
        clock[0] = 6.0
        assert sc.evaluate() == "down"
        assert events == ["retire"]
        # At min_replicas idle never retires.
        router.stats.update(live=1)
        clock[0] = 30.0
        sc.evaluate()
        clock[0] = 40.0
        assert sc.evaluate() is None
        assert events == ["retire"]


# ----------------------------------- in-process fleet integration + SLO


class TestFleetInProcess:
    def test_route_drain_and_one_scrape_slo(self):
        coord = Coordinator(lost_after_s=5.0).start()
        addr = coord.address
        reps, router = [], None
        try:
            for i, name in enumerate(("rep-a", "rep-b")):
                reps.append(ReplicaServer(
                    addr, name=name, net=mlp_net(seed=i + 1),
                    replica_index=i, heartbeat_s=0.25,
                    handle_sigterm=False).start())
            router = FleetRouter(addr, poll_interval_s=0.1,
                                 request_timeout_s=10.0).start()
            _wait(lambda: sum(1 for r in router.table()
                              if r["state"] == "live") == 2,
                  10.0, what="2 live replicas")
            x = [[0.1, 0.2, 0.3]]
            out = router.predict(x)
            assert out.shape == (1, 2)
            # Through the router's own HTTP front too.
            req = urllib.request.Request(
                router.url + "/predict",
                data=json.dumps({"data": x}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10.0) as resp:
                assert np.asarray(
                    json.loads(resp.read())["predictions"]).shape == (1, 2)
            # Graceful drain via the admin route: the replica leaves
            # cleanly (never counted dead) and traffic continues on rep-a.
            req = urllib.request.Request(reps[1].url + "/admin/drain",
                                         data=b"{}", method="POST")
            with urllib.request.urlopen(req, timeout=5.0) as resp:
                assert json.loads(resp.read())["status"] == "draining"
            _wait(lambda: reps[1]._stopped.is_set(), 10.0,
                  what="rep-b drained")
            _wait(lambda: sum(1 for r in router.table()
                              if r["state"] == "live") == 1,
                  10.0, what="table shrinks to 1")
            for _ in range(5):
                assert router.predict(x).shape == (1, 2)
            stats = router.load_stats()
            assert stats["dead"] == 0  # drain is not death
            assert stats["counts"]["ok"] >= 6
            # Satellite: all three fleet SLO families in ONE scrape.
            with urllib.request.urlopen(router.url + "/metrics",
                                        timeout=5.0) as resp:
                text = resp.read().decode()
            for family in ("dl4j_fleet_replicas",
                           "dl4j_router_requests_total",
                           "dl4j_router_failover_seconds"):
                assert family in text, f"{family} missing from scrape"
            assert 'dl4j_fleet_replicas{state="live"} 1' in text
        finally:
            if router is not None:
                router.stop()
            for rep in reps:
                if not rep._stopped.is_set():
                    rep.drain(timeout_s=5.0)
            coord.close()


# ------------------------------------------- sharded-group routing units


class TestShardGroupRouting:
    def test_parse_replica_role(self):
        from deeplearning4j_tpu.serving.router import parse_replica_role

        assert parse_replica_role("replica") == ("live", None, 1)
        assert parse_replica_role("replica:warming") == ("warming", None, 1)
        assert parse_replica_role("replica:shard2/4") == ("live", 2, 4)
        assert parse_replica_role("replica:shard0/4:draining") == (
            "draining", 0, 4)
        assert parse_replica_role("trainer") is None

    def _shard(self, group, i, n, port, state="live", lease=0.0):
        return ReplicaInfo(
            worker_id=f"{group}#{i}@127.0.0.1:{port}", name=f"{group}#{i}",
            url=f"http://127.0.0.1:{port}", state=state,
            lease_age_s=lease, seen_at=time.monotonic(), load=0.0,
            shard_index=i, shard_count=n, group=group)

    def test_complete_group_routes_through_its_entry_member(self):
        r = _router_with([self._shard("g", i, 4, 1000 + i)
                          for i in range(4)])
        assert r._pick(exclude=set()).name == "g#0"

    def test_incomplete_group_is_unroutable(self):
        # Member g#3 missing (lease-reaped): the other three are alive
        # and fresh, but the UNIT is gone — no candidate at all.
        r = _router_with([self._shard("g", i, 4, 1000 + i)
                          for i in range(3)])
        assert r._pick(exclude=set()) is None

    def test_one_stale_member_lease_fails_the_whole_group(self):
        rows = [self._shard("g", i, 4, 1000 + i) for i in range(4)]
        rows[2].lease_age_s = 1e9
        assert _router_with(rows)._pick(exclude=set()) is None
        # An unsharded replica alongside the broken group still routes.
        solo = _info("solo", 2000, load=99.0)
        assert _router_with(rows + [solo])._pick(
            exclude=set()).name == "solo"

    def test_warming_member_keeps_group_out_of_rotation(self):
        rows = [self._shard("g", i, 2, 1000 + i) for i in range(2)]
        rows[1].state = "warming"
        assert _router_with(rows)._pick(exclude=set()) is None


# ------------------------------------------------- multi-process chaos CI


def _spawn_fleet(tmp_path, ckpt, n, plan, lost_after_s, heartbeat_s):
    coord = Coordinator(lost_after_s=lost_after_s).start()
    addr = coord.address
    manager = FleetManager(addr, ckpt, heartbeat_s=heartbeat_s,
                           env=_sub_env(plan),
                           log_dir=str(tmp_path / "logs"))
    for _ in range(n):
        manager.spawn()
    return coord, addr, manager


class TestFleetChaos:
    def test_three_replica_fleet_survives_kill_and_hang(self, tmp_path):
        """Acceptance chaos drill: 3 CPU replicas; the fault plan SIGKILLs
        replica 0 on its 10th request and hangs replica 1 for 3s on its
        12th. Non-shed availability must stay >= 99%, every failover must
        complete inside 1s, and the kill must surface as a lease-expiry
        eviction (dead replica) at a 1.0s lease."""
        ckpt = _save(mlp_net(seed=1), tmp_path / "ckpt")
        plan = [
            {"kind": "kill_replica", "step": 10, "worker": 0},
            {"kind": "hang_replica", "step": 12, "worker": 1,
             "seconds": 3.0},
        ]
        coord, addr, manager = _spawn_fleet(
            tmp_path, ckpt, n=3, plan=plan, lost_after_s=1.0,
            heartbeat_s=0.25)
        router = FleetRouter(addr, poll_interval_s=0.1,
                             request_timeout_s=10.0,
                             attempt_timeout_s=0.75, quarantine_s=4.0,
                             http=False).start()
        try:
            _wait(lambda: sum(1 for r in router.table()
                              if r["state"] == "live") == 3,
                  120.0, what="3 live replicas")
            x = [[0.3, -0.1, 0.7]]
            ok = failed = 0
            for _ in range(150):
                try:
                    router.predict(x, timeout_s=10.0)
                    ok += 1
                except ServerOverloadedError:
                    raise  # shed under this load would be a routing bug
                except Exception:
                    failed += 1
            assert ok / (ok + failed) >= 0.99, (ok, failed)
            counts = router.counts()
            # >= 1, not >= 2: the kill's failover retry can land on
            # replica 1 exactly as its hang fires, so ONE request chain
            # absorbs both faults and counts a single failover outcome.
            assert counts["failover"] >= 1, counts  # kill/hang rerouted
            assert counts["shed"] == 0
            # Failover detection -> reroute -> answer inside 1s.
            fam = obs.metrics.get_family("dl4j_router_failover_seconds")
            _, _, fo_sum, fo_count = fam.children()[0].histogram_state()
            assert fo_count >= 1
            assert fo_sum / fo_count < 1.0, (fo_sum, fo_count)
            # The killed replica died hard (137) and was lease-reaped.
            _wait(lambda: manager.procs["replica-0"].poll() is not None,
                  30.0, what="replica-0 killed")
            assert manager.procs["replica-0"].returncode == 137
            _wait(lambda: router.load_stats()["dead"] >= 1, 10.0,
                  what="lease-expiry eviction observed")
        finally:
            router.stop()
            manager.stop_all()
            coord.close()

    def test_rolling_update_zero_5xx_zero_compiles_and_sigterm_drain(
            self, tmp_path):
        """Rolling update acceptance: two replicas serve checkpoint A
        under continuous traffic; a rolling update to checkpoint B must
        complete with ZERO client-visible errors, the rolled replicas must
        do ZERO serving-path compiles after rejoining, and the swap must
        actually change the served model. Then SIGTERM retires a replica:
        exit code 0, never counted dead."""
        pa = _save(mlp_net(seed=1), tmp_path / "ckpt_a")
        pb = _save(mlp_net(seed=7), tmp_path / "ckpt_b")
        coord, addr, manager = _spawn_fleet(
            tmp_path, pa, n=2, plan=None, lost_after_s=2.0,
            heartbeat_s=0.25)
        router = FleetRouter(addr, poll_interval_s=0.1,
                             request_timeout_s=15.0,
                             attempt_timeout_s=5.0, http=False).start()
        try:
            _wait(lambda: sum(1 for r in router.table()
                              if r["state"] == "live") == 2,
                  120.0, what="2 live replicas")
            x = [[0.25, 0.5, -0.75]]
            before = router.predict(x, timeout_s=15.0)

            stop = threading.Event()
            client_errors = []

            def traffic():
                while not stop.is_set():
                    try:
                        router.predict(x, timeout_s=15.0)
                    except Exception as e:
                        client_errors.append(repr(e))

            t = threading.Thread(target=traffic, daemon=True)
            t.start()
            try:
                results = manager.rolling_update(pb, router, timeout_s=300.0)
            finally:
                stop.set()
                t.join(30.0)
            assert client_errors == [], client_errors[:5]
            assert len(results) == 2
            for summary in results.values():
                assert summary["ok"] is True
                assert summary["path"] == pb
                assert summary["compiled_during_warm"] >= 0
            _wait(lambda: sum(1 for r in router.table()
                              if r["state"] == "live") == 2,
                  30.0, what="both replicas rejoined")
            # The rollout actually changed the served model.
            after = router.predict(x, timeout_s=15.0)
            assert not np.allclose(before, after)
            # Zero serving-path compiles after rejoin: per-replica compile
            # counters must not move under fresh traffic.
            urls = [r["url"] for r in router.table()
                    if r["state"] == "live"]

            def compiles():
                total = 0.0
                for u in urls:
                    with urllib.request.urlopen(u + "/metrics",
                                                timeout=5.0) as resp:
                        total += sum_metric_families(
                            resp.read().decode(),
                            ("dl4j_xla_compiles_total",))
                return total

            c0 = compiles()
            for _ in range(30):
                router.predict(x, timeout_s=15.0)
            assert compiles() == c0
            # SIGTERM = graceful drain: exit 0, clean leave, not dead.
            assert manager.retire("replica-1", timeout_s=60.0) == 0
            _wait(lambda: sum(1 for r in router.table()
                              if r["state"] == "live") == 1,
                  15.0, what="retired replica left the table")
            assert router.load_stats()["dead"] == 0
            assert router.predict(x, timeout_s=15.0).shape == (1, 2)
        finally:
            router.stop()
            manager.stop_all()
            coord.close()


# ------------------------------------- sharded-group multi-process chaos


def _lm_ckpt(tmp_path):
    from deeplearning4j_tpu.models import zoo
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    conf = zoo.transformer_lm(vocab_size=32, t=16, d_model=32, n_heads=4,
                              n_blocks=2, decode_cache_length=2048)
    return _save(ComputationGraph(conf).init(), tmp_path / "lm_ckpt")


class TestShardGroupChaos:
    def test_kill_one_member_fails_group_cleanly(self, tmp_path):
        """Acceptance chaos drill: a 4-process tensor-parallel shard
        group (one LM, `--model-parallel 4`, paged KV) serves
        generations as ONE routable unit. SIGKILLing one member
        mid-decode must (a) surface the in-flight generation as a clean
        502 (`PartialFailureError`) — never a hang, never a silently
        truncated completion passed off as success — and (b) make the
        router drop the whole group from rotation within ~one lease, so
        new generations shed instead of reaching a broken group."""
        ckpt = _lm_ckpt(tmp_path)
        coord = Coordinator(lost_after_s=1.0).start()
        addr = coord.address
        manager = FleetManager(addr, ckpt, heartbeat_s=0.25,
                               env=_sub_env(),
                               log_dir=str(tmp_path / "logs"))
        router = FleetRouter(addr, poll_interval_s=0.1,
                             request_timeout_s=120.0, http=False).start()
        try:
            manager.spawn_group("lm", 4, extra_args=[
                "--decode-slots", "2", "--kv-cache", "paged",
                "--kv-page-size", "64"])
            _wait(lambda: sum(1 for r in router.table()
                              if r["state"] == "live"
                              and r.get("group") == "lm") == 4,
                  240.0, what="4 live shard members")
            rows = {r["name"]: r for r in router.table()}
            assert rows["lm#0"]["shard_count"] == 4
            assert rows["lm#0"]["shard_index"] == 0
            entry_url = rows["lm#0"]["url"]

            # Sanity: the complete group answers end to end (the ids
            # echo the prompt followed by the generated tokens).
            out = router.generate([1, 2, 3], 4, timeout_s=120.0,
                                  temperature=0.0)
            assert out[:3] == [1, 2, 3] and len(out) == 7

            result = {}

            def long_generate():
                try:
                    result["ids"] = router.generate(
                        [1, 2, 3, 4, 5], 1990, timeout_s=120.0,
                        temperature=0.0)
                except Exception as e:
                    result["error"] = e

            t = threading.Thread(target=long_generate, daemon=True)
            t.start()

            def decoding():
                with urllib.request.urlopen(entry_url + "/metrics",
                                            timeout=5.0) as resp:
                    text = resp.read().decode()
                return sum_metric_families(
                    text, ("dl4j_serving_decode_slots_busy",)) >= 1

            _wait(decoding, 120.0, what="generation admitted to a slot")
            t_kill = time.monotonic()
            manager.kill("lm#3")

            # (b) Group unroutable within ~one lease: the survivors'
            # peer watchdog 503s new admissions and the dead member's
            # lease expiry breaks group completeness; either way a fresh
            # generation shows a clean shed, never a broken answer.
            def group_unroutable():
                try:
                    router.generate([9], 2, timeout_s=10.0,
                                    temperature=0.0)
                    return False
                except (ServerOverloadedError, PartialFailureError):
                    return True

            _wait(group_unroutable, 10.0, every_s=0.05,
                  what="router drops the broken group")
            detect_s = time.monotonic() - t_kill
            assert detect_s < 4.0, detect_s  # ~1.0s lease + poll slack

            # (a) The in-flight generation fails FAST and EXPLICITLY.
            t.join(30.0)
            assert not t.is_alive(), "in-flight generation hung"
            assert "error" in result, (
                "generation completed despite a dead shard member: "
                f"{result.get('ids', [])[:8]}...")
            assert isinstance(result["error"], PartialFailureError), \
                repr(result["error"])
            assert "shard group" in str(result["error"])

            # The dead member is lease-reaped; the table shows the
            # incomplete group and a hard death, and new work sheds.
            _wait(lambda: "lm#3" not in {r["name"]
                                         for r in router.table()},
                  10.0, what="dead member reaped from the table")
            assert manager.procs["lm#3"].returncode in (-9, 137)
            with pytest.raises(ServerOverloadedError):
                router.generate([9], 2, timeout_s=10.0, temperature=0.0)
            assert router.counts()["shed"] >= 1
        finally:
            router.stop()
            manager.stop_all()
            coord.close()
