"""Second-order solver tests.

Reference analogs: `optimize/solvers/LBFGS.java`, `ConjugateGradient.java`,
`LineGradientDescent.java`, `BackTrackLineSearch.java` and the `Solver`
dispatch on `OptimizationAlgorithm`. Round-1/2 verdicts flagged that
`optimization_algo` was accepted and silently ignored — these tests pin the
implemented behavior.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize import solvers


class TestBacktrackLineSearch:
    def test_armijo_on_quadratic(self):
        """f(w) = ||w||^2/2: from w=[2,2] along -grad, the full step (alpha=1)
        lands exactly at the minimum and satisfies Armijo."""
        loss_fn = lambda w: 0.5 * jnp.vdot(w, w)
        w = jnp.array([2.0, 2.0])
        g = w
        w_new, loss_new, step = solvers.backtrack_line_search(
            loss_fn, w, loss_fn(w), g, -g, max_iters=8)
        assert float(step) > 0
        assert float(loss_new) < float(loss_fn(w))
        # Armijo sufficient decrease holds at the accepted point.
        assert float(loss_new) <= float(
            loss_fn(w) + 1e-4 * step * jnp.vdot(-g, g))

    def test_failure_returns_zero_step(self):
        """A direction of ascent never satisfies Armijo: no move, step 0."""
        loss_fn = lambda w: 0.5 * jnp.vdot(w, w)
        w = jnp.array([1.0, 1.0])
        g = w
        w_new, loss_new, step = solvers.backtrack_line_search(
            loss_fn, w, loss_fn(w), g, +g, max_iters=4)
        assert float(step) == 0.0
        np.testing.assert_allclose(np.asarray(w_new), np.asarray(w))


class TestMinimize:
    def rosenbrock(self, w):
        return (1 - w[0]) ** 2 + 100.0 * (w[1] - w[0] ** 2) ** 2

    @pytest.mark.parametrize("algo", ["lbfgs", "conjugate_gradient"])
    def test_converges_on_rosenbrock(self, algo):
        w0 = jnp.array([-1.2, 1.0])
        w, loss = solvers.minimize(algo, self.rosenbrock, w0,
                                   iterations=200, max_line_search=20)
        assert float(loss) < 1e-3, (algo, float(loss))

    def test_line_gradient_descent_decreases(self):
        w0 = jnp.array([-1.2, 1.0])
        w, loss = solvers.minimize("line_gradient_descent", self.rosenbrock,
                                   w0, iterations=50, max_line_search=10)
        assert float(loss) < float(self.rosenbrock(w0))

    def test_lbfgs_quadratic_exact(self):
        """On a convex quadratic, L-BFGS with enough iterations reaches the
        optimum to high precision."""
        A = jnp.array([[3.0, 1.0], [1.0, 2.0]])
        b = jnp.array([1.0, -1.0])
        loss_fn = lambda w: 0.5 * w @ A @ w - b @ w
        w, loss = solvers.minimize("lbfgs", loss_fn, jnp.zeros(2),
                                   iterations=30, max_line_search=20)
        w_star = jnp.linalg.solve(A, b)
        np.testing.assert_allclose(np.asarray(w), np.asarray(w_star),
                                   atol=1e-4)

    def test_sgd_rejected(self):
        with pytest.raises(ValueError, match="SGD"):
            solvers.minimize("stochastic_gradient_descent",
                             lambda w: jnp.vdot(w, w), jnp.zeros(2))


def _net(algo, iterations=20):
    conf = (NeuralNetConfiguration.builder()
            .seed(3).optimization_algo(algo).iterations(iterations)
            .max_num_line_search_iterations(10)
            .list()
            .layer(DenseLayer(n_out=12, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


class TestEngineIntegration:
    """`optimization_algo` is honored by fit() (round-1/2 verdict item)."""

    @pytest.mark.parametrize("algo", ["lbfgs", "conjugate_gradient",
                                      "line_gradient_descent"])
    def test_mln_fit_uses_solver(self, algo, rng):
        net = _net(algo)
        X = rng.randn(32, 4).astype("float32")
        Y = np.eye(3)[rng.randint(0, 3, 32)].astype("float32")
        s0 = net.score(DataSet(X, Y))
        net.fit(X, Y)
        s1 = net.score(DataSet(X, Y))
        assert s1 < s0 * 0.9, (algo, s0, s1)
        assert net.iteration == 20  # solver counts config iterations

    def test_lbfgs_beats_sgd_on_small_batch(self, rng):
        """Full-batch L-BFGS on a tiny problem reaches a much lower loss in
        the same number of iterations than plain SGD — the point of having
        the second-order path at all."""
        X = rng.randn(32, 4).astype("float32")
        Y = np.eye(3)[rng.randint(0, 3, 32)].astype("float32")
        lbfgs = _net("lbfgs", iterations=40)
        lbfgs.fit(X, Y)
        sgd_conf = (NeuralNetConfiguration.builder()
                    .seed(3).learning_rate(0.1).updater("sgd").iterations(40)
                    .list()
                    .layer(DenseLayer(n_out=12, activation="tanh"))
                    .layer(OutputLayer(n_out=3, activation="softmax",
                                       loss_function="mcxent"))
                    .set_input_type(InputType.feed_forward(4))
                    .build())
        sgd = MultiLayerNetwork(sgd_conf).init()
        sgd.fit(X, Y)
        assert lbfgs.score(DataSet(X, Y)) < sgd.score(DataSet(X, Y))

    def test_graph_fit_uses_solver(self, rng):
        gb = (NeuralNetConfiguration.builder()
              .seed(3).optimization_algo("lbfgs").iterations(15)
              .graph_builder()
              .add_inputs("in")
              .add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
              .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                            loss_function="mcxent"), "d")
              .set_outputs("out"))
        gb.set_input_types(InputType.feed_forward(4))
        net = ComputationGraph(gb.build()).init()
        X = rng.randn(24, 4).astype("float32")
        Y = np.eye(3)[rng.randint(0, 3, 24)].astype("float32")
        s0 = net.score(DataSet(X, Y))
        net.fit(X, Y)
        assert net.score(DataSet(X, Y)) < s0 * 0.9
