"""Kernel registry tests: env-knob override matrix, CPU graceful fallback,
per-signature memoized resolution (the hoisting counter contract), AOT
fingerprint invalidation on knob flips, CLI smoke, Pallas-vs-XLA parity for
every registered kernel, and the acceptance bit-identity contract
(`DL4J_TPU_KERNELS=xla` trains bit-identically to `auto` on CPU through
both engines, per-batch and k=4 superstep). PERF.md §19."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu import observability as obs
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DeviceCacheDataSetIterator
from deeplearning4j_tpu.kernels import fused_update, lstm_cell, norm_act, registry
from deeplearning4j_tpu.kernels import flash_attention as kflash
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization,
    DenseLayer,
    DropoutLayer,
    GravesLSTM,
    OutputLayer,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph

from conftest import make_classification_data

N_IN, N_OUT = 4, 3

_ENV_VARS = ["DL4J_TPU_KERNELS"] + [
    "DL4J_TPU_KERNEL_" + k.upper() for k in registry.kernel_names()]


@pytest.fixture(autouse=True)
def _clean_kernel_env(monkeypatch):
    """Every test starts from the default (auto) config with an empty
    resolution memo, and leaves no memo entries keyed by its env behind."""
    for var in _ENV_VARS:
        monkeypatch.delenv(var, raising=False)
    registry.clear_cache()
    yield
    registry.clear_cache()


def _mlp_conf(superstep_k=0, updater="adam"):
    return (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.05).updater(updater)
            .weight_init("xavier").superstep_k(superstep_k)
            .list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(BatchNormalization())
            .layer(DropoutLayer(dropout=0.5))
            .layer(OutputLayer(n_out=N_OUT, activation="softmax",
                               loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(N_IN)).build())


def _graph_conf(superstep_k=0):
    return (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.05).updater("adam").weight_init("xavier")
            .superstep_k(superstep_k)
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_out=8, activation="relu"), "in")
            .add_layer("out", OutputLayer(n_out=N_OUT, activation="softmax",
                                          loss_function="mcxent"), "d")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(N_IN))
            .build())


def _lstm_conf(updater="adam"):
    return (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.05).updater(updater).weight_init("xavier")
            .list()
            .layer(GravesLSTM(n_out=6, activation="tanh"))
            .layer(RnnOutputLayer(n_out=N_OUT, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.recurrent(N_IN))
            .build())


def _make_batches(seed, n_batches=7, batch=6):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n_batches):
        X, Y = make_classification_data(rng, n=batch, n_features=N_IN,
                                        n_classes=N_OUT, dtype="float32")
        out.append(DataSet(X, Y))
    return out


def _assert_trees_identical(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# --------------------------------------------------------------------------
# Env-knob matrix


class TestModeKnobs:
    def test_default_is_auto(self):
        for k in registry.kernel_names():
            assert registry.mode_for(k) == ("auto", "default")

    def test_global_knob(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_KERNELS", "xla")
        for k in registry.kernel_names():
            assert registry.mode_for(k) == ("xla", "DL4J_TPU_KERNELS")

    def test_per_kernel_override_wins(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_KERNELS", "xla")
        monkeypatch.setenv("DL4J_TPU_KERNEL_LSTM_CELL", "pallas")
        assert registry.mode_for("lstm_cell") == (
            "pallas", "DL4J_TPU_KERNEL_LSTM_CELL")
        assert registry.mode_for("norm_act") == ("xla", "DL4J_TPU_KERNELS")

    @pytest.mark.parametrize("var", ["DL4J_TPU_KERNELS",
                                     "DL4J_TPU_KERNEL_NORM_ACT"])
    def test_invalid_value_raises(self, monkeypatch, var):
        monkeypatch.setenv(var, "cuda")
        with pytest.raises(ValueError, match="cuda"):
            registry.mode_for("norm_act")

    def test_config_key_tracks_env(self, monkeypatch):
        base = registry.config_key()
        assert base == tuple((k, "auto") for k in registry.kernel_names())
        monkeypatch.setenv("DL4J_TPU_KERNELS", "xla")
        flipped = registry.config_key()
        assert flipped != base
        assert dict(flipped) == {k: "xla" for k in registry.kernel_names()}
        fp = registry.config_fingerprint()
        assert fp == dict(flipped)
        json.dumps(fp)  # must stay JSON-able for the AOT sidecar


# --------------------------------------------------------------------------
# Resolution: CPU graceful fallback, forced modes, memoization


class TestResolution:
    def test_unknown_kernel_raises(self):
        with pytest.raises(KeyError, match="unknown kernel"):
            registry.resolve("conv3d", backend="cpu")

    def test_auto_on_cpu_falls_back_to_xla(self):
        for name in ("lstm_cell", "fused_update", "norm_act",
                     "bottleneck_block"):
            res = registry.resolve(name, backend="cpu")
            assert res.impl == "xla", res
        # flash_attention's Pallas kernel historically interprets off-TPU
        # (its pre-registry behavior) — auto keeps that.
        assert registry.resolve("flash_attention", backend="cpu").impl == "pallas"

    def test_forced_pallas_interprets_off_tpu(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_KERNELS", "pallas")
        res = registry.resolve(
            "lstm_cell", backend="cpu", shapes=(6, 6), dtypes=("float32",),
            meta=(("gate", "sigmoid"), ("act", "tanh"),
                  ("peephole", True), ("masked", False)))
        assert res.impl == "pallas"
        assert "forced" in res.reason

    def test_forced_pallas_structural_refusal_falls_back(self, monkeypatch):
        # A gate activation the kernel cannot express: even forced mode
        # must fall back (with the probe's reason surfaced), not crash.
        monkeypatch.setenv("DL4J_TPU_KERNEL_LSTM_CELL", "pallas")
        res = registry.resolve(
            "lstm_cell", backend="cpu", shapes=(6, 6), dtypes=("float32",),
            meta=(("gate", "hardtanh"), ("act", "tanh"),
                  ("peephole", False), ("masked", False)))
        assert res.impl == "xla"
        assert "unavailable" in res.reason
        assert "hardtanh" in res.reason

    def test_forced_xla_everywhere(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_KERNELS", "xla")
        for name in registry.kernel_names():
            res = registry.resolve(name, backend="cpu")
            assert res.impl == "xla"
            assert "forced via DL4J_TPU_KERNELS" in res.reason

    def test_fused_update_cpu_reasons(self):
        res = registry.resolve(
            "fused_update", backend="cpu", shapes=((8, 3),),
            dtypes=("float32",), meta=(("kind", "adam"),
                                       ("hyper", (0.9, 0.999, 1e-8))))
        assert res.impl == "xla"
        # Unfused updaters never get the Pallas path even on TPU.
        res = registry.resolve(
            "fused_update", backend="tpu", shapes=((8, 3),),
            dtypes=("float32",), meta=(("kind", "adagrad"), ("hyper", (1e-6,))))
        assert res.impl == "xla"
        ok, reason = fused_update._pallas_available(
            "tpu", ((8, 3),), ("float32",), meta=(("kind", "adagrad"),))
        assert not ok and "no fused kernel" in reason

    def test_resolution_memoized_per_signature(self):
        registry.clear_cache()
        sig = dict(backend="cpu", shapes=(8, 128), dtypes=("float32",),
                   meta=(("gate", "sigmoid"), ("act", "tanh"),
                         ("peephole", False), ("masked", False)))
        registry.resolve("lstm_cell", **sig)
        probes = registry.probe_count()
        for _ in range(5):
            registry.resolve("lstm_cell", **sig)
        assert registry.probe_count() == probes  # memo hit: zero new probes
        registry.resolve("lstm_cell", **dict(sig, shapes=(16, 128)))
        assert registry.probe_count() > probes  # new signature re-probes

    def test_clear_cache_reprobes(self):
        registry.resolve("norm_act", backend="cpu")
        probes = registry.probe_count()
        registry.clear_cache()
        registry.resolve("norm_act", backend="cpu")
        assert registry.probe_count() > probes

    def test_describe_covers_all_kernels(self):
        rows = registry.describe(backend="cpu")
        assert [r["kernel"] for r in rows] == sorted(registry.kernel_names())
        for r in rows:
            assert r["mode"] == "auto" and r["impl"] and r["reason"]


# --------------------------------------------------------------------------
# Program identity: jit-cache keys and the AOT fingerprint


class TestProgramIdentity:
    def test_fingerprint_doc_invalidates_on_knob_flip(self, monkeypatch):
        from deeplearning4j_tpu.compilation.store import (
            build_fingerprint_doc, fingerprint)

        net = MultiLayerNetwork(_mlp_conf()).init()
        X = jnp.zeros((6, N_IN), jnp.float32)
        Y = jnp.zeros((6, N_OUT), jnp.float32)
        doc_auto = build_fingerprint_doc(net, "train_step", {}, (X, Y))
        assert doc_auto["kernels"] == {k: "auto"
                                       for k in registry.kernel_names()}
        monkeypatch.setenv("DL4J_TPU_KERNELS", "xla")
        doc_xla = build_fingerprint_doc(net, "train_step", {}, (X, Y))
        assert doc_xla["kernels"]["lstm_cell"] == "xla"
        assert fingerprint(doc_auto) != fingerprint(doc_xla)

    def test_jit_cache_key_includes_kernel_config(self, monkeypatch):
        net = MultiLayerNetwork(_mlp_conf()).init()
        ds = _make_batches(9, n_batches=1)[0]
        net.fit(ds)
        n_auto = len(net._jit_cache)
        net.fit(ds)
        assert len(net._jit_cache) == n_auto  # same env: cache hit
        monkeypatch.setenv("DL4J_TPU_KERNELS", "xla")
        registry.clear_cache()
        net.fit(ds)
        assert len(net._jit_cache) > n_auto  # knob flip: distinct program
        keys = {k[-1] for k in net._jit_cache}
        assert len(keys) == 2  # one kernel config per env


# --------------------------------------------------------------------------
# Hoisting: repeated same-signature blocks never re-run probes


class TestProbeHoisting:
    def test_superstep_restack_adds_zero_probes(self):
        net = MultiLayerNetwork(_mlp_conf(superstep_k=4)).init()
        batches = _make_batches(0, n_batches=8)
        net.fit(batches)  # traces k=4 blocks: probes run here
        probes = registry.probe_count()
        net.fit(batches)  # restacked same-shape blocks: memo hits only
        net.fit(batches)
        assert registry.probe_count() == probes

    def test_device_cache_epochs_add_zero_probes(self):
        net = MultiLayerNetwork(_mlp_conf()).init()
        it = DeviceCacheDataSetIterator(_make_batches(0, n_batches=4))
        net.fit(it)
        probes = registry.probe_count()
        for _ in range(3):
            net.fit(it)
        assert registry.probe_count() == probes


# --------------------------------------------------------------------------
# CLI smoke


class TestCLI:
    _REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def _run(self, *argv):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        for var in _ENV_VARS:
            env.pop(var, None)
        return subprocess.run(
            [sys.executable, "-m", "deeplearning4j_tpu.kernels", *argv],
            cwd=self._REPO, env=env, capture_output=True, text=True,
            timeout=120)

    def test_table_lists_all_kernels(self):
        proc = self._run()
        assert proc.returncode == 0, proc.stderr
        for name in registry.kernel_names():
            assert name in proc.stdout

    def test_json_output(self):
        proc = self._run("--json")
        assert proc.returncode == 0, proc.stderr
        rows = json.loads(proc.stdout)
        assert {r["kernel"] for r in rows} == set(registry.kernel_names())
        for r in rows:
            assert set(r) >= {"kernel", "mode", "mode_source", "impl",
                              "reason"}


# --------------------------------------------------------------------------
# Dispatch metric


class TestDispatchMetric:
    def test_resolve_increments_counter(self):
        registry.resolve("norm_act", backend="cpu")
        fam = obs.metrics.to_json()["dl4j_kernel_dispatch_total"]
        series = {(s["labels"]["kernel"], s["labels"]["impl"]): s["value"]
                  for s in fam["series"]}
        before = series[("norm_act", "xla")]
        registry.resolve("norm_act", backend="cpu")  # memo hit still counts
        fam = obs.metrics.to_json()["dl4j_kernel_dispatch_total"]
        series = {(s["labels"]["kernel"], s["labels"]["impl"]): s["value"]
                  for s in fam["series"]}
        assert series[("norm_act", "xla")] == before + 1


# --------------------------------------------------------------------------
# Parity: every kernel's Pallas path (interpret on CPU) vs its XLA fallback

# The gate below fails when a kernel is added to the registry without a
# parity test here (or, for flash_attention, in test_flash_attention.py;
# for bottleneck_block, in test_bottleneck_block.py).
PARITY_COVERED = {"lstm_cell", "fused_update", "norm_act", "flash_attention",
                  "flash_attention_paged", "bottleneck_block"}


def test_every_kernel_has_parity_coverage():
    assert set(registry.kernel_names()) == PARITY_COVERED


# bf16 rows of the parity matrix compare bf16-in/bf16-out paths whose
# internals accumulate differently (Pallas: f32 `preferred_element_type`;
# XLA fallback: operand-dtype math) — tolerances sized to bf16's ~8-bit
# mantissa, not to f32 roundoff.
_PARITY_TOLS = {"float32": dict(rtol=1e-5, atol=1e-5),
                "bfloat16": dict(rtol=4e-2, atol=4e-2)}


class TestParity:
    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    @pytest.mark.parametrize("peephole,masked", [
        (False, False), (True, False), (False, True), (True, True)])
    def test_lstm_cell(self, monkeypatch, peephole, masked, dtype):
        rng = np.random.RandomState(3)
        dt = jnp.dtype(dtype)
        b, n = 5, 7
        xw = jnp.asarray(rng.randn(b, 4 * n), dt)
        h0 = jnp.asarray(rng.randn(b, n), dt)
        c0 = jnp.asarray(rng.randn(b, n), dt)
        RW = jnp.asarray(rng.randn(n, 4 * n) * 0.1, dt)
        pw = tuple(jnp.asarray(rng.randn(n) * 0.1, dt)
                   for _ in range(3)) if peephole else None
        m = (jnp.asarray(rng.rand(b) < 0.6, dt) if masked else None)

        def cell_for(mode):
            monkeypatch.setenv("DL4J_TPU_KERNEL_LSTM_CELL", mode)
            registry.clear_cache()
            return lstm_cell.resolve_cell(
                batch=b, n_out=n, dtype=dtype, peephole=peephole,
                masked=masked, gate_activation="sigmoid", activation="tanh",
                gate_act=jax.nn.sigmoid, cell_act=jnp.tanh)

        ref = cell_for("xla")(xw, h0, c0, RW, pw, m)
        got = cell_for("pallas")(xw, h0, c0, RW, pw, m)
        for r, g in zip(ref, got):
            assert g.dtype == dt
            np.testing.assert_allclose(np.asarray(g, np.float32),
                                       np.asarray(r, np.float32),
                                       **_PARITY_TOLS[dtype])

    @pytest.mark.parametrize("kind,fields,hyper", [
        ("adam", ("m", "v"), (0.9, 0.999, 1e-8)),
        ("nesterovs", ("v",), (0.9,)),
        ("rmsprop", ("g2",), (0.95, 1e-8)),
    ])
    def test_fused_update(self, monkeypatch, kind, fields, hyper):
        rng = np.random.RandomState(4)
        tree = lambda: {"W": jnp.asarray(rng.randn(9, 5), jnp.float32),
                        "b": jnp.asarray(rng.randn(5), jnp.float32)}
        grads = tree()
        state = {f: tree() for f in fields}

        def run(mode):
            monkeypatch.setenv("DL4J_TPU_KERNEL_FUSED_UPDATE", mode)
            registry.clear_cache()
            return fused_update.dispatch(kind, state, grads,
                                         jnp.float32(0.05), jnp.int32(2),
                                         hyper)

        ref_state, ref_deltas = run("xla")
        got_state, got_deltas = run("pallas")
        for r, g in zip(jax.tree_util.tree_leaves((ref_state, ref_deltas)),
                        jax.tree_util.tree_leaves((got_state, got_deltas))):
            np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                       rtol=1e-5, atol=1e-6)

    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    @pytest.mark.parametrize("op,act", [("batchnorm", "relu"),
                                        ("layernorm", "tanh"),
                                        ("batchnorm", "identity")])
    def test_norm_act(self, monkeypatch, op, act, dtype):
        rng = np.random.RandomState(5)
        dt = jnp.dtype(dtype)
        x = jnp.asarray(rng.randn(6, 10), dt)
        gamma = jnp.asarray(rng.rand(10) + 0.5, dt)
        beta = jnp.asarray(rng.randn(10), dt)
        mean = jnp.asarray(rng.randn(10), dt)
        var = jnp.asarray(rng.rand(10) + 0.1, dt)

        def run(mode):
            monkeypatch.setenv("DL4J_TPU_KERNEL_NORM_ACT", mode)
            registry.clear_cache()
            if op == "batchnorm":
                return norm_act.batchnorm_norm_act(x, mean, var, gamma, beta,
                                                   1e-5, act)
            return norm_act.layernorm_norm_act(x, gamma, beta, 1e-5, act)

        got, ref = run("pallas"), run("xla")
        assert got.dtype == dt
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(ref, np.float32),
                                   **_PARITY_TOLS[dtype])

    def test_fused_update_refuses_bf16_gracefully(self, monkeypatch):
        # Optimizer state is always f32 master copies (mixed-precision
        # policies cast COMPUTE, not params), so the fused kernel refuses
        # bf16 leaves — the bf16 row of the parity matrix for this kernel
        # is the graceful fallback, not a numeric comparison.
        monkeypatch.setenv("DL4J_TPU_KERNEL_FUSED_UPDATE", "pallas")
        registry.clear_cache()
        res = registry.resolve(
            "fused_update", backend="cpu", shapes=((8, 3),),
            dtypes=("bfloat16",),
            meta=(("kind", "adam"), ("hyper", (0.9, 0.999, 1e-8))))
        assert res.impl == "xla"
        assert "bfloat16" in res.reason

    def test_lstm_cell_grad(self, monkeypatch):
        # pallas_call has no autodiff rule; the cell must still sit inside
        # the engines' value_and_grad (kernels/_diff.py pairs the Pallas
        # forward with the XLA reference's VJP).
        rng = np.random.RandomState(7)
        b, n = 4, 6
        xw = jnp.asarray(rng.randn(b, 4 * n), jnp.float32)
        h0 = jnp.asarray(rng.randn(b, n), jnp.float32)
        c0 = jnp.asarray(rng.randn(b, n), jnp.float32)
        RW = jnp.asarray(rng.randn(n, 4 * n) * 0.1, jnp.float32)

        def loss_with(mode):
            monkeypatch.setenv("DL4J_TPU_KERNEL_LSTM_CELL", mode)
            registry.clear_cache()
            cell = lstm_cell.resolve_cell(
                batch=b, n_out=n, dtype="float32", peephole=False,
                masked=False, gate_activation="sigmoid", activation="tanh",
                gate_act=jax.nn.sigmoid, cell_act=jnp.tanh)

            def loss(rw):
                h, c, out = cell(xw, h0, c0, rw, None, None)
                return jnp.sum(out ** 2) + jnp.sum(c)

            return jax.grad(loss)(RW)

        np.testing.assert_allclose(np.asarray(loss_with("pallas")),
                                   np.asarray(loss_with("xla")),
                                   rtol=1e-4, atol=1e-5)

    def test_norm_act_grad(self, monkeypatch):
        rng = np.random.RandomState(8)
        x = jnp.asarray(rng.randn(6, 10), jnp.float32)
        gamma = jnp.asarray(rng.rand(10) + 0.5, jnp.float32)
        beta = jnp.asarray(rng.randn(10), jnp.float32)

        def grads_with(mode):
            monkeypatch.setenv("DL4J_TPU_KERNEL_NORM_ACT", mode)
            registry.clear_cache()
            return jax.grad(
                lambda xv, g: jnp.sum(
                    norm_act.layernorm_norm_act(xv, g, beta, 1e-5, "tanh")
                    ** 2),
                argnums=(0, 1))(x, gamma)

        for p, r in zip(grads_with("pallas"), grads_with("xla")):
            np.testing.assert_allclose(np.asarray(p), np.asarray(r),
                                       rtol=1e-4, atol=1e-5)

    def test_forced_pallas_net_trains_float_close(self, monkeypatch):
        # The end-to-end regression for the autodiff seam: a BN net trained
        # with every kernel forced to Pallas (interpret on CPU) must run —
        # not crash in value_and_grad — and land float-close to XLA.
        def train(mode):
            if mode is None:
                monkeypatch.delenv("DL4J_TPU_KERNELS", raising=False)
            else:
                monkeypatch.setenv("DL4J_TPU_KERNELS", mode)
            registry.clear_cache()
            net = MultiLayerNetwork(_mlp_conf()).init()
            for ds in _make_batches(8, n_batches=4):
                net.fit(ds)
            return np.asarray(net.params())

        np.testing.assert_allclose(train("pallas"), train("xla"),
                                   rtol=1e-3, atol=1e-4)

    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    def test_flash_attention_xla_mode_matches_pallas(self, monkeypatch, dtype):
        rng = np.random.RandomState(6)
        dt = jnp.dtype(dtype)
        q, k, v = (jnp.asarray(rng.randn(2, 16, 2, 8), dt) for _ in range(3))

        def run(mode):
            if mode is None:
                monkeypatch.delenv("DL4J_TPU_KERNELS", raising=False)
            else:
                monkeypatch.setenv("DL4J_TPU_KERNELS", mode)
            registry.clear_cache()
            return kflash.flash_attention(q, k, v, causal=True)

        got, ref = run(None), run("xla")  # auto: pallas vs dense reference
        assert got.dtype == dt
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(ref, np.float32),
                                   **_PARITY_TOLS[dtype])

    @pytest.mark.parametrize("t", [1, 3])
    def test_flash_attention_paged_pallas_matches_xla(self, monkeypatch, t):
        # Paged gather over a pool with pad tail, zero-page rows, and a
        # multi-token (speculative verify) query width.
        rng = np.random.RandomState(9)
        B, H, D, page, P, NP = 3, 2, 8, 4, 7, 4
        q = jnp.asarray(rng.randn(B, t, H, D), jnp.float32)
        kp = jnp.asarray(rng.randn(P, page, H, D), jnp.float32)
        vp = jnp.asarray(rng.randn(P, page, H, D), jnp.float32)
        table = jnp.asarray([[1, 2, 3, 0], [4, 0, 0, 0], [0, 0, 0, 0]],
                            jnp.int32)
        pos = jnp.asarray([9, 2, 0], jnp.int32)  # row 2: empty slot

        def run(mode):
            monkeypatch.setenv("DL4J_TPU_KERNEL_FLASH_ATTENTION_PAGED", mode)
            registry.clear_cache()
            return kflash.paged_decode_attention(q, kp, vp, table, pos, True)

        np.testing.assert_allclose(np.asarray(run("pallas")),
                                   np.asarray(run("xla")),
                                   rtol=1e-5, atol=1e-5)


# --------------------------------------------------------------------------
# Acceptance: DL4J_TPU_KERNELS=xla is bit-identical to auto on CPU


class TestBitIdentity:
    def _train(self, conf_fn, batches_fn, mode, monkeypatch):
        if mode is None:
            monkeypatch.delenv("DL4J_TPU_KERNELS", raising=False)
        else:
            monkeypatch.setenv("DL4J_TPU_KERNELS", mode)
        registry.clear_cache()
        net = conf_fn()
        for _ in range(2):
            for ds in batches_fn():
                net.fit(ds)
        return net.params_tree, net.opt_state

    def _pair(self, conf_fn, batches_fn, monkeypatch):
        ref = self._train(conf_fn, batches_fn, "xla", monkeypatch)
        got = self._train(conf_fn, batches_fn, None, monkeypatch)
        _assert_trees_identical(ref, got)

    def test_mln_adam_bn(self, monkeypatch):
        self._pair(lambda: MultiLayerNetwork(_mlp_conf()).init(),
                   lambda: _make_batches(1, n_batches=3), monkeypatch)

    def test_graph_engine(self, monkeypatch):
        self._pair(lambda: ComputationGraph(_graph_conf()).init(),
                   lambda: _make_batches(2, n_batches=3), monkeypatch)

    def test_lstm_net(self, monkeypatch):
        def batches():
            rng = np.random.RandomState(3)
            b, t = 4, 9
            X = rng.randn(b, t, N_IN).astype("float32")
            Y = np.eye(N_OUT)[rng.randint(0, N_OUT, (b, t))].astype("float32")
            return [DataSet(X, Y)]

        self._pair(lambda: MultiLayerNetwork(_lstm_conf()).init(),
                   batches, monkeypatch)

    def test_superstep_k4(self, monkeypatch):
        def train(mode):
            if mode is None:
                monkeypatch.delenv("DL4J_TPU_KERNELS", raising=False)
            else:
                monkeypatch.setenv("DL4J_TPU_KERNELS", mode)
            registry.clear_cache()
            net = MultiLayerNetwork(_mlp_conf(superstep_k=4)).init()
            net.fit(_make_batches(4, n_batches=7))
            return net.params_tree, net.opt_state

        _assert_trees_identical(train("xla"), train(None))
