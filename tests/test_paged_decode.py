"""Generation fast path (ISSUE 15): paged/shared KV cache, prefix cache,
speculative decoding.

Acceptance coverage:

- paged-vs-dense BIT-IDENTITY through the scheduler, greedy and seeded
  (same per-request draw order as sequential `generate_lm`);
- copy-on-write divergence after a shared prefix: two slots sharing one
  tail page append different tokens and each matches its own dense
  reference, with the pool's shared/used counts moving through the CoW;
- prefix cache: a repeat prompt skips prefill (hit counter, identical
  output), entries hold pool refs, eviction reclaims pages;
- page-leak check: the pool's free count returns to baseline after slot
  recycling, deadline expiry, and prefix-cache clear;
- speculative exactness gate: greedy decode through the draft-model
  scheduler is bit-identical to the non-speculative scheduler and to
  `generate_lm`; an identical-weights draft actually accepts tokens;
- the new metric families ride one `/metrics` scrape.
"""

import time
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.models import zoo
from deeplearning4j_tpu.models.kv_pool import (
    KVPagePool,
    PoolExhaustedError,
    PrefixCache,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.serving import InferenceServer
from deeplearning4j_tpu.serving.scheduler import GenerationScheduler

V = 17
CAP = 32
PAGE = 8


def _lm(d_model=16, seed=12345):
    conf = zoo.transformer_lm(vocab_size=V, t=16, d_model=d_model,
                              n_heads=2, n_blocks=1,
                              decode_cache_length=CAP, seed=seed)
    return ComputationGraph(conf).init()


@pytest.fixture(scope="module")
def lm():
    return _lm()


@pytest.fixture(scope="module")
def lm_twin():
    # Same config + seed as `lm`: identical weights, so as a draft its
    # argmax always agrees with the target (accept rate 1).
    return _lm()


@pytest.fixture(scope="module")
def draft_lm():
    return _lm(d_model=8, seed=999)


def _ref(lm, prompt, n, **kw):
    return zoo.generate_lm(lm, prompt, n, window=16, use_cache=True, **kw)


# ------------------------------------------------------------------ pool


class TestKVPagePool:
    def test_geometry_and_zero_page(self):
        pool = KVPagePool(slots=2, capacity=32, page_size=8)
        assert pool.pages_per_seq == 4
        assert pool.num_pages == 2 * 4 + 1
        assert pool.free_count == pool.num_pages - 1  # page 0 reserved
        with pytest.raises(ValueError):
            KVPagePool(slots=2, capacity=30, page_size=8)
        with pytest.raises(ValueError):
            pool.ref([0])

    def test_install_free_and_counts(self):
        pool = KVPagePool(slots=2, capacity=32, page_size=8)
        pages = pool.install_slot(0, 10)  # ceil(10/8) = 2 pages
        assert len(pages) == 2
        assert list(pool.table[0, :2]) == pages
        assert pool.counts() == {"free": 6, "used": 2, "shared": 0}
        pool.free_slot(0)
        assert pool.counts()["free"] == 8
        assert not pool.table.any()

    def test_shared_install_and_cow_plan(self):
        pool = KVPagePool(slots=2, capacity=32, page_size=8)
        pages = pool.install_slot(0, 5)      # one partially-filled page
        orig = pages[0]
        pool.install_shared(1, list(pages), 5)
        assert pool.counts() == {"free": 7, "used": 0, "shared": 1}
        copies = pool.plan_appends(1)        # both slots write into it
        # Both slots CoW the shared page onto private copies (the pool
        # mutates its per-slot page lists in place, so compare against
        # the captured original id).
        assert len(copies) == 2
        assert all(src == orig for src, _ in copies)
        assert pool.table[0, 0] != pool.table[1, 0]
        assert pool.counts()["shared"] == 0
        assert pool.length_of(0) == pool.length_of(1) == 6

    def test_append_crosses_page_boundary(self):
        pool = KVPagePool(slots=1, capacity=32, page_size=8)
        pool.install_slot(0, 8)              # exactly one full page
        assert pool.plan_appends(1) == []    # fresh page, nothing to copy
        assert len(pool.pages_of(0)) == 2

    def test_rewind_releases_pages(self):
        pool = KVPagePool(slots=1, capacity=32, page_size=8)
        pool.install_slot(0, 8)
        pool.plan_appends(9)                 # -> length 17, 3 pages
        assert len(pool.pages_of(0)) == 3
        pool.rewind(0, 8)
        assert len(pool.pages_of(0)) == 1
        assert pool.length_of(0) == 8
        assert pool.counts()["used"] == 1

    def test_exhaustion_and_reclaim(self):
        pool = KVPagePool(slots=2, capacity=32, page_size=8, pages=3)
        pool.install_slot(0, 16)             # both usable pages
        with pytest.raises(PoolExhaustedError):
            pool.install_slot(1, 8)
        hoard = [pool.pages_of(0)]

        def reclaim():
            if not hoard:
                return False
            pool.free_slot(0)
            hoard.clear()
            return True

        pool.reclaim = reclaim
        pages = pool.install_slot(1, 8)      # succeeds via reclaim
        assert len(pages) == 1


class TestPrefixCache:
    def test_hit_miss_and_refs(self):
        pool = KVPagePool(slots=1, capacity=32, page_size=8)
        cache = PrefixCache(pool, max_entries=2)
        pages = pool.install_slot(0, 5)
        probs = np.full(V, 1.0 / V)
        cache.admit([1, 2, 3, 4, 5], pages, 5, probs)
        pool.free_slot(0)
        # The cache ref keeps the page resident after slot retirement.
        assert pool.counts()["used"] == 1
        assert cache.get([9, 9]) is None
        got = cache.get([1, 2, 3, 4, 5])
        assert got is not None
        g_pages, g_len, g_probs = got
        assert list(g_pages) == pages and g_len == 5
        np.testing.assert_array_equal(g_probs, probs)
        assert cache.hits == 1 and cache.misses == 1
        cache.clear()
        assert pool.free_count == pool.num_pages - 1

    def test_lru_eviction_frees_pages(self):
        pool = KVPagePool(slots=1, capacity=32, page_size=8)
        cache = PrefixCache(pool, max_entries=2)
        for i in range(3):
            pages = pool.install_slot(0, 3)
            cache.admit([i], pages, 3, np.zeros(V))
            pool.free_slot(0)
        assert len(cache) == 2
        assert cache.get([0]) is None        # evicted (LRU)
        assert pool.counts()["used"] == 2


# ----------------------------------------------------- paged bit-identity


class TestPagedBitIdentity:
    def _run(self, lm, kv, prompt, n, **sampling):
        sched = GenerationScheduler(lm, model_name=f"bit_{kv}", slots=3,
                                    kv=kv, page_size=PAGE).start()
        try:
            return sched.generate(prompt, n, timeout_s=120, **sampling)
        finally:
            sched.stop()

    def test_greedy_matches_dense_and_sequential(self, lm):
        prompt = [1, 5, 2, 9, 4]
        ref = _ref(lm, prompt, 10, temperature=0.0)
        assert self._run(lm, "dense", prompt, 10, temperature=0.0) == ref
        assert self._run(lm, "paged", prompt, 10, temperature=0.0) == ref

    def test_seeded_sampling_same_draw_order(self, lm):
        prompt = [2, 7, 1]
        ref = _ref(lm, prompt, 12, temperature=1.0, seed=7)
        out = self._run(lm, "paged", prompt, 12, temperature=1.0, seed=7)
        assert out == ref

    def test_concurrent_slots_page_boundary_crossings(self, lm):
        # Three interleaved sequences of different depths: appends cross
        # page boundaries at different rounds per slot.
        sched = GenerationScheduler(lm, model_name="bit_mix", slots=3,
                                    kv="paged", page_size=PAGE).start()
        try:
            import threading

            prompts = [[1, 2, 3], [4, 5, 6, 7, 8, 9, 10], [11]]
            steps = [20, 9, 14]
            outs = [None] * 3

            def client(i):
                outs[i] = sched.generate(prompts[i], steps[i],
                                         temperature=1.0, seed=100 + i,
                                         timeout_s=120)

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(3)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            sched.stop()
        for i in range(3):
            assert outs[i] == _ref(lm, prompts[i], steps[i],
                                   temperature=1.0, seed=100 + i)


# -------------------------------------------- prefix cache + CoW + leaks


class TestPrefixCacheServing:
    def test_repeat_prompt_hits_and_matches(self, lm):
        sched = GenerationScheduler(lm, model_name="pc", slots=2,
                                    kv="paged", page_size=PAGE).start()
        try:
            prompt = [3, 1, 4, 1, 5]
            first = sched.generate(prompt, 8, temperature=0.0,
                                   timeout_s=120)
            h0, m0 = sched._prefix_cache.hits, sched._prefix_cache.misses
            again = sched.generate(prompt, 8, temperature=0.0,
                                   timeout_s=120)
            assert again == first == _ref(lm, prompt, 8, temperature=0.0)
            assert sched._prefix_cache.hits == h0 + 1
            assert sched._prefix_cache.misses == m0
        finally:
            sched.stop()

    def test_cow_divergence_after_shared_prefix(self, lm):
        # Two requests share the cached prefix (one partially-filled tail
        # page); different seeds diverge immediately. CoW must give each
        # its own tail copy — both outputs match their sequential refs.
        sched = GenerationScheduler(lm, model_name="cow", slots=2,
                                    kv="paged", page_size=PAGE).start()
        try:
            import threading

            prompt = [6, 2, 8, 3, 1]  # 5 tokens: tail page shared
            sched.generate(prompt, 1, temperature=0.0, timeout_s=120)
            outs = [None] * 2

            def client(i):
                outs[i] = sched.generate(prompt, 10, temperature=1.0,
                                         seed=40 + i, timeout_s=120)

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            sched.stop()
        for i in range(2):
            assert outs[i] == _ref(lm, prompt, 10, temperature=1.0,
                                   seed=40 + i)

    def test_cow_pool_accounting(self, lm):
        # Deterministic CoW bookkeeping through the stepper (no decode
        # thread): share a tail page across two slots, then step them
        # with DIFFERENT tokens; each must match the dense stepper's row.
        from deeplearning4j_tpu.models.zoo import (DecodeStepper,
                                                   PagedDecodeStepper)

        prompt = [1, 2, 3, 4, 5]
        paged = PagedDecodeStepper(lm, 2, page_size=PAGE)
        dense = DecodeStepper(lm, 2)
        probs, state, n = paged.prefill(prompt, pad_to=8)
        paged.install(0, state, n)
        paged.install_shared(1, paged.pool.pages_of(0), n)
        assert paged.pool.counts()["shared"] == 1
        dprobs, dstate, dn = dense.prefill(prompt, pad_to=8)
        dense.install(0, dstate, dn)
        dense.install(1, dstate, dn)
        p = paged.step([7, 11])
        d = dense.step([7, 11])
        assert paged.pool.counts()["shared"] == 0  # both tails CoW'd
        np.testing.assert_array_equal(p, d)
        p2 = paged.step([int(p[0].argmax()), int(p[1].argmax())])
        d2 = dense.step([int(d[0].argmax()), int(d[1].argmax())])
        np.testing.assert_array_equal(p2, d2)

    def test_no_page_leak_after_recycle_and_deadline(self, lm):
        sched = GenerationScheduler(lm, model_name="leak", slots=2,
                                    kv="paged", page_size=PAGE).start()
        pool = sched.stepper.pool
        baseline = pool.num_pages - 1
        try:
            for i in range(3):
                sched.generate([1 + i, 2, 3], 6, temperature=1.0, seed=i,
                               timeout_s=120)
            # Deadline expiry mid-generation: slot recycled at the next
            # step boundary, pages freed.
            with pytest.raises(Exception):
                sched.generate([9, 9, 9, 9], 25, temperature=1.0,
                               timeout_s=0.001)
            deadline = time.monotonic() + 10
            while pool.tracked() and time.monotonic() < deadline:
                time.sleep(0.02)
            assert not pool.tracked(), "slot not recycled after deadline"
            sched._prefix_cache.clear()
            assert pool.free_count == baseline, pool.counts()
        finally:
            sched.stop()


# ------------------------------------------------------------ speculative


class TestSpeculativeDecoding:
    def test_exactness_gate_vs_non_speculative(self, lm, draft_lm):
        prompt = [1, 5, 2, 9, 4]
        ref = _ref(lm, prompt, 10, temperature=0.0)
        sched = GenerationScheduler(lm, model_name="spec", slots=2,
                                    kv="paged", page_size=PAGE,
                                    draft=draft_lm, spec_k=3).start()
        try:
            assert sched.generate(prompt, 10, temperature=0.0,
                                  timeout_s=120) == ref
            # Near-capacity: k_round clamps to the remaining budget.
            edge = [3, 3, 8]
            assert sched.generate(edge, CAP - 3, temperature=0.0,
                                  timeout_s=120) == _ref(
                                      lm, edge, CAP - 3, temperature=0.0)
            # Sampled requests stay on the sequential draw order (one
            # token per round from row 0).
            assert sched.generate(prompt, 8, temperature=1.0, seed=5,
                                  timeout_s=120) == _ref(
                                      lm, prompt, 8, temperature=1.0,
                                      seed=5)
        finally:
            sched.stop()

    def test_identical_draft_accepts(self, lm, lm_twin):
        from deeplearning4j_tpu.serving import metrics as _m

        sched = GenerationScheduler(lm, model_name="spec_twin", slots=2,
                                    kv="paged", page_size=PAGE,
                                    draft=lm_twin, spec_k=3).start()
        try:
            prompt = [2, 4, 6]
            out = sched.generate(prompt, 12, temperature=0.0,
                                 timeout_s=120)
            assert out == _ref(lm, prompt, 12, temperature=0.0)
        finally:
            sched.stop()
        acc = _m.SPECULATIVE_TOKENS.labels(model="spec_twin",
                                           outcome="accepted")
        # Identical weights -> the target's argmax always agrees with the
        # draft's: speculation actually emits multiple tokens per step.
        assert acc._value > 0

    def test_spec_requires_draft_knobs(self, lm, draft_lm):
        with pytest.raises(ValueError):
            GenerationScheduler(lm, kv="dense", prefix_cache=True)
        with pytest.raises(ValueError):
            GenerationScheduler(lm, kv="paged", draft=draft_lm, spec_k=0)
        with pytest.raises(ValueError):
            GenerationScheduler(lm, kv="nope")


# ---------------------------------------------------------------- metrics


class TestPagedMetricsScrape:
    def test_one_scrape_carries_paged_families(self, lm, lm_twin):
        server = InferenceServer(lm, port=0, kv_cache="paged",
                                 kv_page_size=PAGE, draft=lm_twin,
                                 spec_k=2).start()
        try:
            prompt = [1, 2, 3, 4]
            server.generate(prompt, 6, temperature=0.0)
            server.generate(prompt, 6, temperature=0.0)  # prefix hit
            with urllib.request.urlopen(server.url + "/metrics",
                                        timeout=10) as r:
                scrape = r.read().decode()
        finally:
            server.stop()
        for needle in (
                'dl4j_kv_pages{model="default",state="free"}',
                'dl4j_kv_pages{model="default",state="used"}',
                'dl4j_kv_pages{model="default",state="shared"}',
                'dl4j_prefix_cache_hits_total{model="default"}',
                'dl4j_prefix_cache_misses_total{model="default"}',
                'dl4j_speculative_tokens_total{model="default",'
                'outcome="accepted"}',
                'dl4j_speculative_tokens_total{model="default",'
                'outcome="rejected"}',
                # existing serving families still ride the same scrape
                'dl4j_serving_ttft_seconds_bucket{model="default"',
                'dl4j_serving_generated_tokens_total{model="default"}',
        ):
            assert needle in scrape, f"missing {needle} in /metrics"


class TestShardedServing:
    def test_model_parallel_serving_matches_unsharded(self):
        """PR 20 end to end at the server tier: a 4-way tensor-parallel
        paged LM serves the same greedy completion as an unsharded one,
        /v1/models + the sharding info gauge report the layout, and the
        sharded jit programs are fingerprint-distinct (the parallel
        context folds into the jit-cache key), so zero serving-path
        recompiles on later identical requests."""
        import json as _json

        ref_srv = InferenceServer(_lm(), port=0, kv_cache="paged",
                                  kv_page_size=PAGE, decode_slots=2).start()
        sh_srv = InferenceServer(_lm(), port=0, kv_cache="paged",
                                 kv_page_size=PAGE, decode_slots=2,
                                 model_parallel=4).start()
        try:
            prompt = [1, 2, 3, 4, 5]
            want = ref_srv.generate(prompt, 8, temperature=0.0)
            got = sh_srv.generate(prompt, 8, temperature=0.0)
            assert list(got) == list(want)
            with urllib.request.urlopen(sh_srv.url + "/v1/models",
                                        timeout=10) as r:
                rows = {m["name"]: m
                        for m in _json.loads(r.read())["models"]}
            assert rows["default"]["sharding"] == "model:4-way"
            with urllib.request.urlopen(sh_srv.url + "/metrics",
                                        timeout=10) as r:
                scrape = r.read().decode()
            assert ('dl4j_serving_model_sharding{model="default",'
                    'sharding="model:4-way"} 1' in scrape
                    or 'dl4j_serving_model_sharding{sharding='
                    '"model:4-way",model="default"} 1' in scrape)
        finally:
            ref_srv.stop()
            sh_srv.stop()
