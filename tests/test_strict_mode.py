"""Runtime strict-mode guard tests (analysis/runtime.py).

Covers: transfer-guard raises on an implicit host->device transfer,
RetraceGuard fires on a shape-varying jitted function, the NaN guard
kills a diverging fit, and everything is a no-op when disabled.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.analysis import (
    RetraceError,
    RetraceGuard,
    install_nan_guard,
    strict_enabled,
    strict_mode,
)
from deeplearning4j_tpu.analysis import runtime as runtime_mod


class FakeNet:
    """Just enough engine surface for watch()/install_nan_guard()."""

    def __init__(self):
        self._jit_cache = {}
        self.score_value = 0.5
        self.iteration = 0
        self.dispatched = 0

    def _fit_dispatch(self, batch):
        self.dispatched += 1
        return batch


class TestStrictEnabled:
    def test_env_unset_defaults_off(self, monkeypatch):
        monkeypatch.delenv("DL4J_TPU_STRICT", raising=False)
        assert strict_enabled() is False
        assert strict_enabled(default=True) is True

    @pytest.mark.parametrize("val,expect", [
        ("1", True), ("true", True), ("ON", True),
        ("0", False), ("false", False), ("off", False), ("", False),
    ])
    def test_env_values(self, monkeypatch, val, expect):
        monkeypatch.setenv("DL4J_TPU_STRICT", val)
        assert strict_enabled() is expect


class TestTransferGuard:
    def test_implicit_transfer_raises_in_strict_mode(self):
        f = jax.jit(lambda x: x * 2)
        f(jnp.ones(3))  # warm up outside the guard
        with strict_mode(enabled=True):
            with pytest.raises(Exception, match="[Tt]ransfer"):
                f(np.ones(3, dtype=np.float32))

    def test_explicit_device_put_is_allowed(self):
        f = jax.jit(lambda x: x * 2)
        with strict_mode(enabled=True):
            y = f(jax.device_put(np.ones(3, dtype=np.float32)))
        assert float(np.asarray(y)[0]) == 2.0

    def test_disabled_is_a_noop(self, monkeypatch):
        monkeypatch.delenv("DL4J_TPU_STRICT", raising=False)
        f = jax.jit(lambda x: x * 3)
        with strict_mode() as guard:
            assert guard is None  # no-op path yields None
            y = f(np.ones(3, dtype=np.float32))  # implicit transfer: fine
        assert float(np.asarray(y)[0]) == 3.0


class TestRetraceGuard:
    def test_fires_on_shape_varying_jit_fn(self):
        guard = RetraceGuard(limit=2, on_violation="raise")
        step = jax.jit(guard.wrap(lambda x: x * 2, name="step"))
        step(jnp.ones(3))   # trace 1
        step(jnp.ones(4))   # trace 2 (new shape)
        with pytest.raises(RetraceError, match="compiled 3 times"):
            step(jnp.ones(5))  # trace 3 > limit

    def test_stable_shapes_do_not_fire(self):
        guard = RetraceGuard(limit=1, on_violation="raise")
        step = jax.jit(guard.wrap(lambda x: x + 1, name="stable"))
        for _ in range(20):
            step(jnp.ones(3))  # cached after the single trace
        assert guard.counts["stable"] == 1

    def test_warn_mode_warns_once(self):
        guard = RetraceGuard(limit=1, on_violation="warn")
        f = guard.wrap(lambda x: x, name="noisy")
        f(1)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            f(2)
            f(3)
        assert len([x for x in w if "retrace storm" in str(x.message)]) == 1

    def test_watch_fires_on_jit_cache_growth(self):
        net = FakeNet()
        guard = RetraceGuard(limit=2, on_violation="raise")
        guard.watch(net)
        try:
            for i in range(2):
                net._jit_cache[("shape", i)] = object()
                net._fit_dispatch(i)  # programs <= limit: fine
            net._jit_cache[("shape", 2)] = object()
            with pytest.raises(RetraceError):
                net._fit_dispatch(2)
        finally:
            guard.unwatch()
        # unwatch restores the original bound method
        net._fit_dispatch(3)
        assert net.dispatched == 4

    def test_limit_from_env(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_RETRACE_LIMIT", "3")
        assert RetraceGuard().limit == 3
        monkeypatch.setenv("DL4J_TPU_RETRACE_LIMIT", "garbage")
        assert RetraceGuard().limit == 10


class TestNanGuard:
    def test_raises_on_nan_loss(self):
        net = FakeNet()
        uninstall = install_nan_guard(net)
        net._fit_dispatch("b0")  # finite loss: fine
        net.score_value = float("nan")
        with pytest.raises(FloatingPointError, match="non-finite loss"):
            net._fit_dispatch("b1")
        uninstall()

    def test_raises_on_inf_loss(self):
        net = FakeNet()
        install_nan_guard(net)
        net.score_value = float("inf")
        with pytest.raises(FloatingPointError):
            net._fit_dispatch("b0")

    def test_check_every_amortizes_the_sync(self):
        net = FakeNet()
        install_nan_guard(net, check_every=3)
        net.score_value = float("nan")
        net._fit_dispatch("b0")  # 1 % 3 != 0: not checked yet
        net._fit_dispatch("b1")
        with pytest.raises(FloatingPointError):
            net._fit_dispatch("b2")  # 3 % 3 == 0: checked

    def test_uninstall_restores_dispatch(self):
        net = FakeNet()
        uninstall = install_nan_guard(net)
        uninstall()
        net.score_value = float("nan")
        net._fit_dispatch("b0")  # guard removed: no raise
        assert net.dispatched == 1


class TestStrictModeComposition:
    def test_net_gets_watch_and_nan_guard_and_teardown(self):
        net = FakeNet()
        orig = net._fit_dispatch
        with strict_mode(net, enabled=True, retrace_limit=100) as guard:
            assert isinstance(guard, RetraceGuard)
            assert net._fit_dispatch is not orig  # patched (watch + nan)
            net.score_value = float("nan")
            with pytest.raises(FloatingPointError):
                net._fit_dispatch("batch")
        assert net._fit_dispatch == orig  # fully restored (bound method eq)

    def test_on_violation_propagates(self):
        net = FakeNet()
        with strict_mode(net, enabled=True, retrace_limit=1,
                         nan_guard=False):
            net._jit_cache["a"] = object()
            net._jit_cache["b"] = object()
            with pytest.raises(RetraceError):
                net._fit_dispatch("batch")

    def test_runtime_module_has_no_import_time_jax_dependency(self):
        # strict_mode imports jax lazily so the linter CLI stays jax-free
        import ast
        import inspect
        tree = ast.parse(inspect.getsource(runtime_mod))
        toplevel = [n for n in tree.body
                    if isinstance(n, (ast.Import, ast.ImportFrom))]
        for n in toplevel:
            names = [a.name for a in n.names] if isinstance(n, ast.Import) \
                else [n.module or ""]
            assert not any(name.split(".")[0] == "jax" for name in names)
