"""Tests for activations, losses, weight init, updaters, schedules, grad norm."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.nn import activations, losses, weights
from deeplearning4j_tpu.nn.conf.distributions import (
    BinomialDistribution,
    Distribution,
    NormalDistribution,
    UniformDistribution,
)
from deeplearning4j_tpu.nn.conf.enums import (
    Activation,
    GradientNormalization,
    LossFunction,
    Updater,
    WeightInit,
)
from deeplearning4j_tpu.ops import grad_norm, schedules, updaters


class TestActivations:
    def test_all_registered_activations_run(self):
        x = jnp.linspace(-2, 2, 11)
        for act in Activation:
            y = activations.resolve(act)(x)
            assert y.shape == x.shape
            assert bool(jnp.all(jnp.isfinite(y))), act

    def test_relu(self):
        x = jnp.asarray([-1.0, 0.0, 2.0])
        np.testing.assert_allclose(activations.resolve("relu")(x), [0, 0, 2])

    def test_softmax_normalizes(self):
        y = activations.resolve("softmax")(jnp.ones((3, 5)))
        np.testing.assert_allclose(np.sum(np.asarray(y), -1), 1.0, rtol=1e-6)

    def test_hardsigmoid_bounds(self):
        y = activations.resolve("hardsigmoid")(jnp.asarray([-10.0, 0.0, 10.0]))
        np.testing.assert_allclose(y, [0.0, 0.5, 1.0])

    def test_custom_registration(self):
        activations.register("double", lambda x: 2 * x)
        np.testing.assert_allclose(activations.resolve("double")(jnp.asarray([3.0])), [6.0])

    def test_unknown_raises(self):
        with pytest.raises(ValueError):
            activations.resolve("nope")


class TestLosses:
    def test_mcxent_softmax_matches_manual(self):
        logits = jnp.asarray([[2.0, 1.0, 0.1], [0.5, 2.5, 0.0]])
        labels = jnp.asarray([[1.0, 0, 0], [0, 1.0, 0]])
        s = losses.score("mcxent", labels, logits, "softmax")
        logp = jax.nn.log_softmax(logits)
        manual = -jnp.mean(jnp.sum(labels * logp, -1))
        np.testing.assert_allclose(float(s), float(manual), rtol=1e-6)

    def test_mse(self):
        pred = jnp.asarray([[1.0, 2.0]])
        lab = jnp.asarray([[0.0, 0.0]])
        s = losses.score("mse", lab, pred, "identity")
        np.testing.assert_allclose(float(s), (1 + 4) / 2, rtol=1e-6)

    def test_xent_from_logits_stable(self):
        logits = jnp.asarray([[100.0, -100.0]])
        labels = jnp.asarray([[1.0, 0.0]])
        s = losses.score("xent", labels, logits, "sigmoid")
        assert np.isfinite(float(s))
        assert float(s) < 1e-3

    def test_mask_zeroes_and_normalizes(self):
        pre = jnp.ones((2, 3, 4))
        lab = jnp.zeros((2, 3, 4))
        mask = jnp.asarray([[1.0, 1, 0], [1, 0, 0]])
        s = losses.score("mse", lab, pre, "identity", mask=mask)
        # Reference semantics (BaseOutputLayer.computeScore): sum of masked
        # per-step losses (3 entries x 1.0) / minibatch size (2) = 1.5.
        np.testing.assert_allclose(float(s), 1.5, rtol=1e-6)

    def test_all_losses_finite(self):
        pre = jnp.asarray([[0.3, -0.2, 0.8]])
        lab = jnp.asarray([[1.0, 0.0, 0.5]])
        for lf in LossFunction:
            s = losses.score(lf, lab, pre, "sigmoid")
            assert np.isfinite(float(s)), lf


class TestWeightInit:
    def test_shapes_and_determinism(self):
        key = jax.random.PRNGKey(0)
        for scheme in [WeightInit.XAVIER, WeightInit.RELU, WeightInit.UNIFORM,
                       WeightInit.XAVIER_UNIFORM, WeightInit.LECUN_NORMAL]:
            w1 = weights.init_weights(key, (20, 30), 20, 30, scheme)
            w2 = weights.init_weights(key, (20, 30), 20, 30, scheme)
            assert w1.shape == (20, 30)
            np.testing.assert_array_equal(w1, w2)

    def test_xavier_scale(self):
        key = jax.random.PRNGKey(1)
        w = weights.init_weights(key, (500, 500), 500, 500, WeightInit.XAVIER)
        np.testing.assert_allclose(np.std(np.asarray(w)), np.sqrt(2.0 / 1000), rtol=0.1)

    def test_zero_ones_identity(self):
        key = jax.random.PRNGKey(0)
        assert float(jnp.sum(weights.init_weights(key, (3, 3), 3, 3, WeightInit.ZERO))) == 0
        assert float(jnp.sum(weights.init_weights(key, (3, 3), 3, 3, WeightInit.ONES))) == 9
        np.testing.assert_array_equal(
            weights.init_weights(key, (3, 3), 3, 3, WeightInit.IDENTITY), np.eye(3))

    def test_distribution(self):
        key = jax.random.PRNGKey(2)
        w = weights.init_weights(key, (1000,), 1, 1, WeightInit.DISTRIBUTION,
                                 NormalDistribution(mean=5.0, std=0.1))
        assert abs(float(jnp.mean(w)) - 5.0) < 0.05

    def test_distribution_serde(self):
        for d in [NormalDistribution(1, 2), UniformDistribution(-3, 3),
                  BinomialDistribution(5, 0.4)]:
            d2 = Distribution.from_dict(d.to_dict())
            assert d2 == d


class TestUpdaters:
    def _converges(self, updater, lr=0.1, steps=400):
        # Minimize f(w) = ||w||^2 with the given updater.
        params = {"w": jnp.asarray([1.0, -2.0, 3.0])}
        state = updater.init(params)
        for t in range(steps):
            grads = {"w": 2 * params["w"]}
            state, deltas = updater.update(state, grads, lr, jnp.asarray(t, jnp.float32))
            params = {"w": params["w"] - deltas["w"]}
        return float(jnp.max(jnp.abs(params["w"])))

    @pytest.mark.parametrize("name", ["sgd", "adam", "adagrad", "adadelta",
                                      "rmsprop", "nesterovs", "adamax"])
    def test_convergence(self, name):
        u = updaters.create(name)
        # rmsprop's sign-like normalized steps oscillate at ~lr near the optimum
        lr = {"adagrad": 0.5, "rmsprop": 0.01}.get(name, 0.1)
        if name == "adadelta":
            # AdaDelta is lr-free and intentionally slow to accelerate from
            # zeroed accumulators; just require solid progress.
            assert self._converges(u, steps=1500) < 0.5
        else:
            assert self._converges(u, lr=lr) < 0.05, name

    def test_none_updater_freezes(self):
        u = updaters.create("none")
        params = {"w": jnp.asarray([1.0])}
        st = u.init(params)
        _, deltas = u.update(st, {"w": jnp.asarray([5.0])}, 0.1, 0)
        np.testing.assert_array_equal(deltas["w"], [0.0])

    def test_sgd_exact(self):
        u = updaters.create("sgd")
        _, deltas = u.update((), {"w": jnp.asarray([2.0])}, 0.5, 0)
        np.testing.assert_allclose(deltas["w"], [1.0])

    def test_adam_bias_correction_first_step(self):
        u = updaters.create("adam")
        st = u.init({"w": jnp.asarray([1.0])})
        _, deltas = u.update(st, {"w": jnp.asarray([1.0])}, 0.001, jnp.asarray(0.0))
        # First Adam step magnitude ~ lr regardless of gradient scale.
        np.testing.assert_allclose(deltas["w"], [0.001], rtol=1e-4)


class TestSchedules:
    def test_none(self):
        fn = schedules.make_schedule(0.1)
        np.testing.assert_allclose(float(fn(jnp.asarray(100.0))), 0.1)

    def test_exponential(self):
        fn = schedules.make_schedule(1.0, "exponential", decay_rate=0.5)
        np.testing.assert_allclose(float(fn(jnp.asarray(2.0))), 0.25)

    def test_step(self):
        fn = schedules.make_schedule(1.0, "step", decay_rate=0.1, steps=10)
        np.testing.assert_allclose(float(fn(jnp.asarray(25.0))), 0.01)

    def test_map_schedule(self):
        fn = schedules.make_schedule(1.0, "schedule", schedule_map={10: 0.5, 20: 0.1})
        assert float(fn(jnp.asarray(5.0))) == 1.0
        assert float(fn(jnp.asarray(15.0))) == 0.5
        assert float(fn(jnp.asarray(25.0))) == pytest.approx(0.1)


class TestGradNorm:
    def test_clip_elementwise(self):
        g = {"W": jnp.asarray([5.0, -5.0, 0.5])}
        out = grad_norm.normalize_layer_gradients(
            g, GradientNormalization.CLIP_ELEMENT_WISE_ABSOLUTE_VALUE, 1.0)
        np.testing.assert_allclose(out["W"], [1.0, -1.0, 0.5])

    def test_clip_l2_per_layer(self):
        g = {"W": jnp.asarray([3.0, 4.0])}
        out = grad_norm.normalize_layer_gradients(g, GradientNormalization.CLIP_L2_PER_LAYER, 1.0)
        np.testing.assert_allclose(np.linalg.norm(np.asarray(out["W"])), 1.0, rtol=1e-5)

    def test_clip_l2_noop_below_threshold(self):
        g = {"W": jnp.asarray([0.3, 0.4])}
        out = grad_norm.normalize_layer_gradients(g, GradientNormalization.CLIP_L2_PER_LAYER, 1.0)
        np.testing.assert_allclose(out["W"], [0.3, 0.4], rtol=1e-6)

    def test_renormalize_per_layer(self):
        g = {"W": jnp.asarray([3.0, 0.0]), "b": jnp.asarray([4.0])}
        out = grad_norm.normalize_layer_gradients(
            g, GradientNormalization.RENORMALIZE_L2_PER_LAYER, 1.0)
        total = np.sqrt(sum(float(jnp.sum(v ** 2)) for v in out.values()))
        np.testing.assert_allclose(total, 1.0, rtol=1e-5)
