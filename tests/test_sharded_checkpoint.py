"""Sharded elastic checkpoint store (`deeplearning4j_tpu/checkpoint/`).

Tier-1 coverage (CPU, 8-device virtual mesh): chunked array store,
atomic-commit crash safety (truncated chunk / missing COMMIT / half-written
tmp), keep-last-k + keep-every-m retention, elastic save-on-N-restore-on-M
round trips (8-way -> 1-way and 4-way, bit-identical), exact continued-fit
resume through both `CheckpointManager` and the legacy `load_checkpoint`
compat path, legacy-ZIP migration, and the atomic earlystopping savers.
The large sweep is marked `slow`.
"""

import os

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.checkpoint import (
    CheckpointCorruptError,
    CheckpointError,
    CheckpointManager,
    is_sharded_checkpoint,
    load_any,
    migrate_zip,
    restore_checkpoint,
)
from deeplearning4j_tpu.checkpoint import array_store, store
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import mesh as mesh_mod
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
from deeplearning4j_tpu.util.checkpoint import (
    CheckpointListener,
    load_checkpoint,
    save_checkpoint,
)


def _net(seed=3, dropout=None, width=12):
    b = (NeuralNetConfiguration.builder()
         .seed(seed).learning_rate(0.1).updater("adam"))
    if dropout is not None:
        b = b.drop_out(dropout)
    conf = (b.list()
            .layer(DenseLayer(n_out=width, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def _batch(step, n=16):
    r = np.random.RandomState(500 + step)
    X = r.randn(n, 4).astype("float32")
    Y = np.eye(3)[r.randint(0, 3, n)].astype("float32")
    return X, Y


def _flat(net):
    return np.asarray(net.params())


class TestArrayStore:
    def test_sharded_leaf_chunks_and_region_read(self, tmp_path):
        """A model-sharded array stores one chunk PER DISTINCT shard (data
        replicas deduped), and arbitrary regions reassemble exactly."""
        mesh = mesh_mod.create_mesh((4, 2), ("data", "model"))
        x = jax.device_put(
            np.arange(8 * 64, dtype=np.float64).reshape(8, 64),
            NamedSharding(mesh, P(None, "model")))
        chunks = list(array_store.leaf_chunks(x))
        assert len(chunks) == 2  # 8 shards, 2 distinct model-axis regions
        os.makedirs(tmp_path / array_store.CHUNK_DIR)
        files = {}
        entry = array_store.write_leaf(str(tmp_path), 0, "params/l/W",
                                       chunks, x.shape, str(x.dtype), files)
        assert len(entry["chunks"]) == 2 and len(files) == 2
        full = array_store.read_full(str(tmp_path), entry)
        np.testing.assert_array_equal(full, np.asarray(x))
        region = array_store.read_region(
            str(tmp_path), entry, (slice(2, 7), slice(30, 50)))
        np.testing.assert_array_equal(region, np.asarray(x)[2:7, 30:50])

    def test_replicated_leaf_is_one_chunk(self, tmp_path):
        mesh = mesh_mod.create_mesh(devices=jax.devices())
        x = jax.device_put(np.arange(6.0), NamedSharding(mesh, P()))
        chunks = list(array_store.leaf_chunks(x))
        assert len(chunks) == 1
        assert chunks[0][0] == ((0, 6),)

    def test_chunks_are_owned_copies_not_device_views(self):
        """Chunk data must not alias the XLA buffer: the async writer reads
        it after training has resumed, and the train step donates (reuses)
        its input buffers — an aliased view would silently capture a LATER
        step's values in the checkpoint."""
        mesh = mesh_mod.create_mesh((4, 2), ("data", "model"))
        for x in (jax.numpy.arange(12.0),
                  jax.device_put(np.arange(8 * 4, dtype=np.float64)
                                 .reshape(8, 4),
                                 NamedSharding(mesh, P(None, "model")))):
            for _, data in array_store.leaf_chunks(x):
                assert data.base is None or isinstance(data.base, np.ndarray)
                assert not any(
                    np.shares_memory(data, np.asarray(sh.data))
                    for sh in x.addressable_shards)


class TestAtomicCommitAndCorruption:
    def _committed(self, tmp_path, steps=(5, 10)):
        net = _net()
        net.fit(*_batch(0))
        mgr = CheckpointManager(str(tmp_path), keep_last=0, async_save=False)
        for s in steps:
            mgr.save(net, step=s)
        return net, mgr

    def test_truncated_chunk_clean_error_and_fallback(self, tmp_path):
        _, mgr = self._committed(tmp_path)
        p = mgr.step_path(10)
        chunk = os.path.join(p, array_store.CHUNK_DIR,
                             sorted(os.listdir(
                                 os.path.join(p, array_store.CHUNK_DIR)))[0])
        with open(chunk, "r+b") as f:
            f.truncate(3)
        # Explicit restore of the damaged step: clean, specific error.
        with pytest.raises(CheckpointCorruptError, match="truncated"):
            restore_checkpoint(p)
        # latest() never serves the damaged step — falls back to step 5.
        assert mgr.latest() == 5
        assert mgr.restore().iteration == 1

    def test_missing_commit_manifest(self, tmp_path):
        _, mgr = self._committed(tmp_path)
        p = mgr.step_path(10)
        os.remove(os.path.join(p, store.COMMIT))
        with pytest.raises(CheckpointCorruptError, match="COMMIT"):
            restore_checkpoint(p)
        assert mgr.latest() == 5

    def test_half_written_tmp_dir_ignored(self, tmp_path):
        _, mgr = self._committed(tmp_path)
        tmp = mgr.step_path(15) + ".tmp"
        os.makedirs(os.path.join(tmp, array_store.CHUNK_DIR))
        with open(os.path.join(tmp, "index.json"), "w") as f:
            f.write("{")  # crashed mid-write
        assert mgr.latest() == 10
        with pytest.raises(CheckpointCorruptError):
            restore_checkpoint(tmp)

    def test_empty_store_raises_clean(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        assert mgr.latest() is None
        with pytest.raises(CheckpointError, match="no committed"):
            mgr.restore()


class TestRetention:
    def test_keep_last_plus_keep_every(self, tmp_path):
        net = _net()
        net.fit(*_batch(0))
        mgr = CheckpointManager(str(tmp_path), keep_last=2, keep_every=4,
                                async_save=False)
        for s in range(1, 11):
            mgr.save(net, step=s)
        # newest 2 survive, plus every 4th forever.
        assert mgr.all_steps() == [4, 8, 9, 10]


class TestElasticRoundTrip:
    """Acceptance: saved on the 8-device mesh, restores bit-identically
    onto a different mesh shape (including single-device)."""

    def test_model_sharded_save_restores_on_1way_and_4way(self, tmp_path):
        mesh42 = mesh_mod.create_mesh((4, 2), ("data", "model"))
        net = _net(width=512)  # Dense W is 4x512 -> model-sharded in halves
        w = ParallelWrapper(net, mesh=mesh42, model_axis="model")
        for s in range(3):
            w.fit(DataSet(*_batch(s)))
        lk = net.layer_keys[0]
        assert net.params_tree[lk]["W"].sharding.spec[-1] == "model"
        ref_p, ref_u = _flat(net), np.asarray(net.updater_state_flat())
        path = w.save_checkpoint(str(tmp_path / "c"))

        # 1-way (single device, no mesh): bit-identical params + updater.
        one = restore_checkpoint(path)
        np.testing.assert_array_equal(_flat(one), ref_p)
        np.testing.assert_array_equal(np.asarray(one.updater_state_flat()),
                                      ref_u)
        assert one.iteration == net.iteration

        # 4-way data mesh: bit-identical, placed on exactly 4 devices.
        mesh4 = mesh_mod.create_mesh(devices=jax.devices()[:4])
        four = restore_checkpoint(path, mesh=mesh4)
        np.testing.assert_array_equal(_flat(four), ref_p)
        assert len(four.params_tree[lk]["W"].sharding.device_set) == 4

        # (2, 2) with model axis: bit-identical AND resharded for the new
        # topology.
        mesh22 = mesh_mod.create_mesh((2, 2), ("data", "model"))
        re22 = restore_checkpoint(path, mesh=mesh22, model_axis="model")
        np.testing.assert_array_equal(_flat(re22), ref_p)
        assert re22.params_tree[lk]["W"].sharding.mesh.shape["model"] == 2

    def test_wrapper_elastic_resume_on_smaller_mesh(self, tmp_path):
        mesh8 = mesh_mod.create_mesh(devices=jax.devices())
        a = _net(seed=11)
        w8 = ParallelWrapper(a, mesh=mesh8)
        for s in range(3):
            w8.fit(DataSet(*_batch(s)))
        w8.save_checkpoint(str(tmp_path / "c"))

        b = _net(seed=99)  # different init — must be overwritten
        w4 = ParallelWrapper(b, mesh=mesh_mod.create_mesh(
            devices=jax.devices()[:4]))
        restored = w4.restore_checkpoint(str(tmp_path / "c"))
        np.testing.assert_array_equal(_flat(restored), _flat(a))
        assert restored.iteration == a.iteration
        w4.fit(DataSet(*_batch(3)))  # training continues on the new mesh
        assert np.isfinite(restored.score_value)


class TestExactResume:
    """Acceptance: continued `fit()` after restore matches the
    uninterrupted run — dropout on, so the RNG continuation is load-bearing
    — through both `CheckpointManager` and the legacy `load_checkpoint`
    compat path."""

    def _train_with_manager(self, tmp_path):
        a = _net(dropout=0.7)
        mgr = CheckpointManager(str(tmp_path), keep_last=3)
        for s in range(10):
            a.fit(*_batch(s))
            if s == 4:
                mgr.save(a)  # async; snapshot is taken synchronously here
        mgr.flush()
        return a, mgr

    def test_via_checkpoint_manager(self, tmp_path):
        a, mgr = self._train_with_manager(tmp_path)
        assert mgr.latest() == 5
        b = mgr.restore()
        assert b.iteration == 5
        for s in range(5, 10):
            b.fit(*_batch(s))
        np.testing.assert_array_equal(_flat(a), _flat(b))

    def test_via_legacy_load_checkpoint_compat(self, tmp_path):
        a, mgr = self._train_with_manager(tmp_path)
        # Both spellings: the committed step dir, and the manager root
        # (latest committed step wins).
        c = load_checkpoint(mgr.step_path(5))
        root = load_checkpoint(str(tmp_path))
        assert root.iteration == 5
        for s in range(5, 10):
            c.fit(*_batch(s))
        np.testing.assert_array_equal(_flat(a), _flat(c))


class TestShardedListener:
    def test_listener_sharded_backend_resume(self, tmp_path):
        net = _net(seed=4, dropout=0.5)
        lst = CheckpointListener(str(tmp_path), frequency=5, keep_last=2,
                                 format="sharded")
        net.set_listeners(lst)
        for s in range(10):
            net.fit(*_batch(s))
        lst.flush()
        assert [os.path.basename(p) for p in lst.saved_paths] == [
            "step_00000005", "step_00000010"]
        assert all(is_sharded_checkpoint(p) for p in lst.saved_paths)
        b = load_checkpoint(lst.saved_paths[0])
        assert b.iteration == 5
        for s in range(5, 10):
            b.fit(*_batch(s))
        np.testing.assert_array_equal(_flat(net), _flat(b))

    def test_sharded_checkpoint_health_check(self, tmp_path):
        from deeplearning4j_tpu.util.failure import _checkpoint_healthy

        net = _net()
        net.fit(*_batch(0))
        mgr = CheckpointManager(str(tmp_path), async_save=False)
        good = mgr.save(net, step=1)
        assert _checkpoint_healthy(good)
        net.set_params(np.full(net.num_params(), np.nan))
        bad = mgr.save(net, step=2)
        assert not _checkpoint_healthy(bad)


class TestLegacyMigration:
    def test_zip_migrates_and_loads_identically(self, tmp_path):
        a = _net(dropout=0.3)
        for s in range(3):
            a.fit(*_batch(s))
        z = str(tmp_path / "legacy.zip")
        save_checkpoint(a, z)
        step_dir = migrate_zip(z, str(tmp_path / "sharded"))
        assert is_sharded_checkpoint(step_dir)
        m = load_any(step_dir)
        # The ZIP stores float64 upcasts; equality after the same
        # round-trip `load_checkpoint` applies to the ZIP itself.
        np.testing.assert_array_equal(_flat(m), _flat(load_checkpoint(z)))
        assert m.iteration == a.iteration
        # And both continue training to the same place (full state came
        # through the migration: params, updater, iteration, RNG).
        n_zip = load_checkpoint(z)
        for s in range(3, 6):
            m.fit(*_batch(s))
            n_zip.fit(*_batch(s))
        np.testing.assert_array_equal(_flat(m), _flat(n_zip))

    def test_serving_from_checkpoint(self, tmp_path):
        from deeplearning4j_tpu.serving import InferenceServer

        net = _net()
        net.fit(*_batch(0))
        mgr = CheckpointManager(str(tmp_path), async_save=False)
        mgr.save(net)
        server = InferenceServer.from_checkpoint(str(tmp_path))
        np.testing.assert_array_equal(_flat(server.net), _flat(net))


class TestEarlyStoppingSaverAtomic:
    def test_zip_saver_survives_crash_mid_save(self, tmp_path, monkeypatch):
        from deeplearning4j_tpu.earlystopping.saver import LocalFileModelSaver
        from deeplearning4j_tpu.util import model_serializer

        net = _net()
        net.fit(*_batch(0))
        saver = LocalFileModelSaver(str(tmp_path))
        saver.save_best_model(net, 0.5)
        good = _flat(saver.get_best_model())

        real = model_serializer.save_model

        def crashing(net, path, **kw):
            real(net, path, **kw)  # bytes hit the tmp file...
            raise OSError("disk full")  # ...then the writer dies

        monkeypatch.setattr(model_serializer, "save_model", crashing)
        net.fit(*_batch(1))
        with pytest.raises(OSError):
            saver.save_best_model(net, 0.4)
        # The committed bestModel.zip is the PREVIOUS good save, intact.
        np.testing.assert_array_equal(_flat(saver.get_best_model()), good)

    def test_sharded_saver_roundtrip(self, tmp_path):
        from deeplearning4j_tpu.earlystopping.saver import LocalFileModelSaver

        net = _net()
        net.fit(*_batch(0))
        saver = LocalFileModelSaver(str(tmp_path), format="sharded")
        assert saver.get_best_model() is None
        saver.save_best_model(net, 0.5)
        saver.save_latest_model(net, 0.5)
        assert is_sharded_checkpoint(str(tmp_path / "bestModel"))
        np.testing.assert_array_equal(_flat(saver.get_best_model()),
                                      _flat(net))
        np.testing.assert_array_equal(_flat(saver.get_latest_model()),
                                      _flat(net))


@pytest.mark.slow
class TestLargeSweep:
    """Wide-model / many-step sweeps — excluded from tier-1."""

    def test_wide_model_many_steps_many_mesh_shapes(self, tmp_path):
        mesh42 = mesh_mod.create_mesh((4, 2), ("data", "model"))
        net = _net(width=2048)
        w = ParallelWrapper(net, mesh=mesh42, model_axis="model")
        mgr = w.checkpoint_manager(str(tmp_path), keep_last=2, keep_every=10)
        for s in range(20):
            w.fit(DataSet(*_batch(s, n=64)))
            if (s + 1) % 5 == 0:
                mgr.save(net)
        mgr.flush()
        assert mgr.all_steps() == [10, 15, 20]
        ref = _flat(net)
        for target in (None, mesh_mod.create_mesh(devices=jax.devices()[:2]),
                       mesh_mod.create_mesh((2, 4), ("data", "model"))):
            got = restore_checkpoint(mgr.step_path(20), mesh=target,
                                     model_axis="model" if target is not None
                                     and "model" in target.shape else None)
            np.testing.assert_array_equal(_flat(got), ref)
