"""Runtime lock tracer tests (analysis/locktrace.py).

Three layers:
- TracedLock mechanics: held stacks, acquisition-order edges, online
  AB/BA cycle detection, the factory's disabled fast path;
- the chaos drill (`faultinject` kind ``lock_invert``): a real bounded
  AB/BA deadlock must flag the cycle AND produce exactly one flight
  bundle whose ``locks.json`` reads the deadlock off one file;
- regressions for the JX018 lock-narrowing fixes in serving/host.py
  (eviction joins off-lock) and serving/router.py (single-flight
  membership refresh with no lock held across the RPC).
"""

import json
import os
import threading
import time

import pytest

from deeplearning4j_tpu.analysis import locktrace
from deeplearning4j_tpu.analysis.locktrace import (
    ENV_ENABLE, ENV_STALL_S, STALL_REASON, TracedLock,
    named_condition, named_lock, named_rlock,
)


@pytest.fixture(autouse=True)
def _clean_tracer():
    locktrace.reset()
    yield
    locktrace.reset()


# ------------------------------------------------------------- mechanics


class TestTracedLockMechanics:
    def test_nested_acquire_records_edge(self):
        a, b = TracedLock("t.a"), TracedLock("t.b")
        with a:
            with b:
                pass
        s = locktrace.stats()
        assert s["edges"] == 1 and s["cycles_total"] == 0
        doc = locktrace.snapshot()
        assert {"from": "t.a", "to": "t.b", "count": 1} in doc["edges"]

    def test_opposite_orders_flag_cycle_at_attempt(self):
        # The SAME thread taking AB then BA proves detection is at
        # acquire *start* — no interleave or deadlock needed.
        a, b = TracedLock("t.a"), TracedLock("t.b")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        s = locktrace.stats()
        assert s["cycles_total"] == 1
        assert "t.a -> t.b -> t.a" in locktrace.snapshot()["cycles"][0] \
            or "t.b -> t.a -> t.b" in locktrace.snapshot()["cycles"][0]

    def test_reentrant_rlock_records_no_self_edge(self):
        r = TracedLock("t.r", threading.RLock())
        with r:
            with r:
                pass
        assert locktrace.stats()["edges"] == 0

    def test_release_pops_held_stack(self):
        a = TracedLock("t.a")
        with a:
            doc = locktrace.snapshot()
            me = [t for t in doc["threads"]
                  if t["ident"] == threading.get_ident()]
            assert me and [h["lock"] for h in me[0]["held"]] == ["t.a"]
        doc = locktrace.snapshot()
        me = [t for t in doc["threads"]
              if t["ident"] == threading.get_ident()]
        assert me and me[0]["held"] == []

    def test_condition_protocol_wait_restores_held(self):
        cond = threading.Condition(TracedLock("t.cond"))
        with cond:
            cond.wait(timeout=0.01)  # _release_save/_acquire_restore
            doc = locktrace.snapshot()
            me = [t for t in doc["threads"]
                  if t["ident"] == threading.get_ident()]
            assert [h["lock"] for h in me[0]["held"]] == ["t.cond"]
        me = [t for t in locktrace.snapshot()["threads"]
              if t["ident"] == threading.get_ident()]
        assert me[0]["held"] == []


class TestFactory:
    def test_disabled_returns_plain_primitives(self, monkeypatch):
        monkeypatch.delenv(ENV_ENABLE, raising=False)
        assert not isinstance(named_lock("x"), TracedLock)
        assert not isinstance(named_rlock("x"), TracedLock)
        cond = named_condition("x")
        assert not isinstance(cond._lock, TracedLock)

    def test_enabled_returns_traced(self, monkeypatch):
        monkeypatch.setenv(ENV_ENABLE, "1")
        lk = named_lock("x")
        assert isinstance(lk, TracedLock) and lk.name == "x"
        cond = named_condition("y")
        assert isinstance(cond._lock, TracedLock)

    def test_drill_requires_tracer(self, monkeypatch):
        monkeypatch.delenv(ENV_ENABLE, raising=False)
        with pytest.raises(RuntimeError):
            locktrace.lock_inversion_drill()


# ---------------------------------------------------------- chaos drill


def _arm_flight(monkeypatch, tmp_path):
    """Point the flight recorder at tmp_path and clear the lock_stall
    rate-limit stamp so this test's stall is 'first' again."""
    from deeplearning4j_tpu.observability.flight import recorder

    monkeypatch.setattr(recorder, "dump_dir", str(tmp_path))
    recorder._last_dump_at.pop(STALL_REASON, None)
    return recorder


class TestInversionDrill:
    def test_drill_flags_cycle_and_dumps_one_bundle(self, monkeypatch,
                                                    tmp_path):
        monkeypatch.setenv(ENV_ENABLE, "1")
        monkeypatch.setenv(ENV_STALL_S, "0.25")
        _arm_flight(monkeypatch, tmp_path)

        from deeplearning4j_tpu.util.faultinject import FaultPlan

        # 2s deadlock window: the watchdog may be mid-way through a stale
        # 1s tick (computed from the default 30s threshold) when the env
        # shrinks it — the stall must outlive one full stale tick.
        plan = FaultPlan.from_json(json.dumps(
            [{"kind": "lock_invert", "step": 3, "worker": 0,
              "seconds": 2.0}]))
        assert plan.maybe_fire(2, 0) == []          # wrong step: no fire
        fired = plan.maybe_fire(3, 0)
        assert len(fired) == 1
        res = fired[0].args["result"]

        assert res["cycle_flagged"], res
        assert res["stall_dumps"] == 1, res         # exactly one bundle
        assert res["bundle"] and os.path.isdir(res["bundle"])
        bundles = [d for d in os.listdir(str(tmp_path))
                   if os.path.isdir(os.path.join(str(tmp_path), d))]
        assert len(bundles) == 1

        with open(os.path.join(res["bundle"], "locks.json")) as f:
            doc = json.load(f)
        assert doc["format"] == 1
        assert doc["cycles_total"] >= 1 and doc["cycles"]
        assert {"from": "drill.a", "to": "drill.b", "count": 1} \
            in doc["edges"]
        assert {"from": "drill.b", "to": "drill.a", "count": 1} \
            in doc["edges"]
        assert doc["stall"]["kind"] in ("acquire_blocked", "held_too_long")
        # every thread row carries a readable stack; the drill threads'
        # held/waiting state was captured mid-deadlock
        assert doc["threads"]
        assert all(t["stack"] for t in doc["threads"])

        # fire-once: replaying the same step injects nothing
        assert plan.maybe_fire(3, 0) == []

    def test_second_stall_in_window_is_rate_limited(self, monkeypatch,
                                                    tmp_path):
        monkeypatch.setenv(ENV_ENABLE, "1")
        monkeypatch.setenv(ENV_STALL_S, "0.25")
        recorder = _arm_flight(monkeypatch, tmp_path)

        res1 = locktrace.lock_inversion_drill(acquire_timeout_s=2.0)
        assert res1["stall_dumps"] == 1
        # Within the recorder's min_interval_s window a second stall
        # episode re-detects but must NOT produce a second bundle.
        assert recorder.min_interval_s > 2.0
        res2 = locktrace.lock_inversion_drill(acquire_timeout_s=0.6,
                                              settle_s=0.6)
        assert res2["stall_dumps"] == 0
        bundles = [d for d in os.listdir(str(tmp_path))
                   if os.path.isdir(os.path.join(str(tmp_path), d))]
        assert len(bundles) == 1


# ------------------------------------------- JX018 fix regressions


class _BlockingRuntime:
    """A batcher/scheduler stand-in whose stop() blocks until released —
    models a drain that takes a while."""

    def __init__(self):
        self.release = threading.Event()
        self.stopped = threading.Event()

    def stop(self):
        self.release.wait(timeout=10.0)
        self.stopped.set()


class TestHostEvictionOffLock:
    def test_snapshot_not_blocked_by_slow_eviction_drain(self):
        """serving/host.py JX018 fix: stop() joins workers with the host
        lock RELEASED, so snapshot()/names() stay responsive during a
        slow drain."""
        import numpy as np

        from deeplearning4j_tpu.serving.host import ModelHost

        class _Net:
            params_tree = {"w": np.zeros((4, 4), np.float32)}

        host = ModelHost()
        model = host.add("m", net=_Net())
        runtime = _BlockingRuntime()
        model.batcher = runtime

        t = threading.Thread(target=host.stop, daemon=True)
        t.start()
        # the drain is in progress (stop() blocked on the runtime)...
        assert runtime.release.wait(timeout=0) is False
        time.sleep(0.05)
        t0 = time.monotonic()
        snap = host.snapshot()                     # ...must not wait on it
        names = host.names()
        elapsed = time.monotonic() - t0
        assert elapsed < 1.0, f"snapshot blocked {elapsed:.2f}s behind stop"
        assert names == ["m"] and snap[0]["name"] == "m"
        runtime.release.set()
        t.join(timeout=10.0)
        assert runtime.stopped.is_set()

    def test_evict_detaches_runtimes_for_off_lock_stop(self):
        import numpy as np

        from deeplearning4j_tpu.serving.host import ModelHost

        class _Net:
            params_tree = {"w": np.zeros((4, 4), np.float32)}

        host = ModelHost()
        model = host.add("m", net=_Net())
        runtime = _BlockingRuntime()
        model.batcher = runtime
        with host._lock:
            stoppables = host._evict(model)
        # detached, not stopped: stopping is the caller's off-lock job
        assert stoppables == [runtime]
        assert model.batcher is None
        assert not runtime.stopped.is_set()
        runtime.release.set()
        host._stop_runtimes(stoppables)
        assert runtime.stopped.is_set()
        host.stop()


class TestRouterSingleFlightRefresh:
    def _router(self):
        from deeplearning4j_tpu.serving.router import FleetRouter

        return FleetRouter("127.0.0.1:1", http=False)

    def test_concurrent_shed_refreshes_share_one_rpc(self):
        """serving/router.py JX018 fix: N concurrent shed-path refreshes
        make ONE coordinator RPC, with no router lock held across it —
        table() stays responsive while the RPC is in flight."""
        router = self._router()
        calls = []
        in_rpc = threading.Event()
        release = threading.Event()

        def slow_status():
            calls.append(1)
            in_rpc.set()
            release.wait(timeout=10.0)
            return {"members": [], "detail": {}}

        router._client.status = slow_status
        threads = [threading.Thread(
            target=router._refresh_membership_shared, daemon=True)
            for _ in range(6)]
        for t in threads:
            t.start()
        assert in_rpc.wait(timeout=5.0)
        time.sleep(0.05)  # let the followers reach the condition wait
        t0 = time.monotonic()
        assert router.table() == []            # not serialized behind RPC
        assert time.monotonic() - t0 < 1.0
        release.set()
        for t in threads:
            t.join(timeout=10.0)
        assert not any(t.is_alive() for t in threads)
        assert len(calls) == 1, f"dogpile: {len(calls)} coordinator RPCs"

    def test_follower_timeout_does_not_hang(self):
        """A leader that dies mid-RPC must not strand followers: the
        condition wait is bounded by 2x the scrape timeout."""
        router = self._router()
        with router._refresh_cond:
            router._refreshing = True              # a leader that vanished
        t0 = time.monotonic()
        router._refresh_membership_shared()
        elapsed = time.monotonic() - t0
        assert elapsed < 10.0                       # bounded, no deadlock
