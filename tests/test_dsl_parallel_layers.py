"""The parallelism matrix as config-DSL citizens: SelfAttentionLayer and
MoELayer built through `NeuralNetConfiguration` and trained by the engines,
including the mesh-sharded paths selected via `ParallelContext`.

Reference analog: the config-DSL contract of
`nn/conf/NeuralNetConfiguration.java:478` — every capability is reachable
from the builder API. The reference predates attention/MoE; these are the
SURVEY.md §2.3/§5 TPU-native extensions, promoted from standalone functions
(round 4) to first-class layers (round 5).
"""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.gradientcheck import check_gradients
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    MoELayer,
    RnnOutputLayer,
    SelfAttentionLayer,
)
from deeplearning4j_tpu.nn.conf.neural_net import (
    MultiLayerConfiguration,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import mesh as mesh_mod
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper


def _builder(dtype="float32", lr=0.01, updater="adam"):
    return (NeuralNetConfiguration.builder()
            .seed(12).learning_rate(lr).updater(updater).dtype(dtype))


def _attention_conf(dtype="float32", causal=True, impl="dense"):
    return (_builder(dtype).list()
            .layer(SelfAttentionLayer(n_out=16, n_heads=4, causal=causal,
                                      attention_impl=impl))
            .layer(RnnOutputLayer(n_out=5, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.recurrent(8, 12))
            .build())


def _moe_conf(dtype="float32", aux_w=1e-2, top_k=2, jitter=0.0):
    return (_builder(dtype).list()
            .layer(MoELayer(n_out=16, n_experts=4, expert_hidden=32,
                            top_k=top_k, aux_loss_weight=aux_w,
                            router_jitter=jitter))
            .layer(RnnOutputLayer(n_out=5, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.recurrent(16, 8))
            .build())


def _seq_data(rng, b=4, t=12, f=8, c=5):
    X = rng.randn(b, t, f).astype("float32")
    Y = np.eye(c)[rng.randint(0, c, (b, t))].astype("float32")
    return X, Y


class TestSelfAttentionLayer:
    def test_forward_matches_manual(self, rng):
        """Layer output == hand-computed multi-head attention (numpy)."""
        net = MultiLayerNetwork(_attention_conf()).init()
        X, _ = _seq_data(rng)
        acts = net.feed_forward(X)
        p = {k: np.asarray(v) for k, v in net.params_tree["layer_0"].items()}
        B, T, H, Dh = 4, 12, 4, 4
        q = (X @ p["Wq"] + p["qB"]).reshape(B, T, H, Dh)
        k = (X @ p["Wk"]).reshape(B, T, H, Dh)
        v = (X @ p["Wv"] + p["vB"]).reshape(B, T, H, Dh)
        s = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(Dh)
        s = np.where(np.triu(np.ones((T, T), bool), 1)[None, None], -1e30, s)
        e = np.exp(s - s.max(-1, keepdims=True))
        a = e / e.sum(-1, keepdims=True)
        o = np.einsum("bhqk,bkhd->bqhd", a, v).reshape(B, T, 16)
        want = o @ p["Wo"] + p["oB"]
        np.testing.assert_allclose(acts[0], want, rtol=1e-4, atol=1e-5)

    def test_gradients(self, rng):
        X, Y = _seq_data(rng, b=3, t=6)
        X, Y = X.astype("float64"), Y.astype("float64")
        conf = (_builder("float64", updater="sgd", lr=0.1).list()
                .layer(SelfAttentionLayer(n_out=8, n_heads=2, causal=True,
                                          attention_impl="dense"))
                .layer(RnnOutputLayer(n_out=5, activation="softmax",
                                      loss_function="mcxent"))
                .set_input_type(InputType.recurrent(8, 6))
                .build())
        net = MultiLayerNetwork(conf).init()
        assert check_gradients(net, DataSet(X, Y), epsilon=1e-6,
                               max_rel_error=1e-5)

    def test_gradients_with_mask(self, rng):
        """The masked-dense path (ragged sequences) is also exact."""
        X, Y = _seq_data(rng, b=3, t=6)
        X, Y = X.astype("float64"), Y.astype("float64")
        fmask = np.ones((3, 6))
        fmask[0, 4:] = 0.0
        fmask[2, 2:] = 0.0
        conf = (_builder("float64", updater="sgd", lr=0.1).list()
                .layer(SelfAttentionLayer(n_out=8, n_heads=2, causal=True))
                .layer(RnnOutputLayer(n_out=5, activation="softmax",
                                      loss_function="mcxent"))
                .set_input_type(InputType.recurrent(8, 6))
                .build())
        net = MultiLayerNetwork(conf).init()
        ds = DataSet(X, Y, fmask, fmask.copy())
        assert check_gradients(net, ds, epsilon=1e-6, max_rel_error=1e-5)

    def test_masked_keys_excluded(self, rng):
        """Non-causal masked attention == dense attention over the valid
        prefix only (padding can't leak into valid positions)."""
        t_valid = 7
        net = MultiLayerNetwork(_attention_conf(causal=False)).init()
        X, _ = _seq_data(rng)
        mask = np.zeros((4, 12), "float32")
        mask[:, :t_valid] = 1.0
        fn = net._get_jit("output", train=False)
        full, _ = fn(net.params_tree, net.state, X, mask, jax.random.PRNGKey(0))
        short, _ = fn(net.params_tree, net.state, X[:, :t_valid],
                      np.ones((4, t_valid), "float32"), jax.random.PRNGKey(0))
        np.testing.assert_allclose(np.asarray(full)[:, :t_valid],
                                   np.asarray(short), rtol=1e-5, atol=1e-6)

    def test_seq_sharded_training_matches_single_device(self, rng):
        """The SAME DSL model trains sequence-sharded (ring attention over
        the mesh's seq axis, chosen at trace time by ParallelContext) with
        parameters matching the single-device run."""
        X, Y = _seq_data(rng)
        net0 = MultiLayerNetwork(_attention_conf(impl="auto")).init()
        for _ in range(5):
            net0.fit(DataSet(X, Y))

        net1 = MultiLayerNetwork(_attention_conf(impl="auto")).init()
        mesh = mesh_mod.create_mesh((2, 2), axis_names=("data", "seq"))
        pw = ParallelWrapper(net1, mesh=mesh, seq_axis="seq")
        for _ in range(5):
            pw.fit(DataSet(X, Y))
        for lk in net0.params_tree:
            for pk in net0.params_tree[lk]:
                np.testing.assert_allclose(
                    np.asarray(net0.params_tree[lk][pk]),
                    np.asarray(net1.params_tree[lk][pk]),
                    rtol=5e-4, atol=5e-5, err_msg=f"{lk}/{pk}")

    def test_serde_roundtrip(self):
        conf = _attention_conf()
        back = MultiLayerConfiguration.from_json(conf.to_json())
        layer = back.layers[0]
        assert isinstance(layer, SelfAttentionLayer)
        assert (layer.n_heads, layer.causal, layer.n_out) == (4, True, 16)


class TestMoELayer:
    def test_trains_and_reduces_loss(self, rng):
        X, Y = _seq_data(rng, b=8, t=8, f=16)
        net = MultiLayerNetwork(_moe_conf(jitter=1e-2)).init()
        s0 = net.score(DataSet(X, Y))
        for _ in range(30):
            net.fit(DataSet(X, Y))
        assert net.score(DataSet(X, Y)) < s0

    def test_aux_loss_in_objective(self, rng):
        """The load-balance aux loss reaches the network objective: the same
        params score differently under different aux weights, by exactly
        (w1 - w0) * aux."""
        X, Y = _seq_data(rng, b=8, t=8, f=16)
        net0 = MultiLayerNetwork(_moe_conf(aux_w=0.0)).init()
        net1 = MultiLayerNetwork(_moe_conf(aux_w=0.5)).init()
        s0, s1 = net0.score(DataSet(X, Y)), net1.score(DataSet(X, Y))
        # aux >= 1.0 at any routing (GShard eq. 4 lower bound), so the gap
        # must be at least 0.5.
        assert s1 - s0 >= 0.5 - 1e-6

    def test_expert_parallel_matches_local(self, rng):
        """One engine step expert-sharded == one step local (longer horizons
        diverge chaotically: routing argmax flips amplify float noise —
        inherent to routed MoE, not a sharding defect)."""
        X, Y = _seq_data(rng, b=8, t=8, f=16)
        net0 = MultiLayerNetwork(_moe_conf()).init()
        net0.fit(DataSet(X, Y))

        net1 = MultiLayerNetwork(_moe_conf()).init()
        mesh = mesh_mod.create_mesh((2, 4), axis_names=("data", "expert"))
        pw = ParallelWrapper(net1, mesh=mesh, expert_axis="expert")
        pw.fit(DataSet(X, Y))
        for lk in net0.params_tree:
            for pk in net0.params_tree[lk]:
                np.testing.assert_allclose(
                    np.asarray(net0.params_tree[lk][pk]),
                    np.asarray(net1.params_tree[lk][pk]),
                    rtol=2e-4, atol=2e-5, err_msg=f"{lk}/{pk}")

    def test_expert_params_sharded(self, rng):
        """ParallelWrapper(expert_axis=...) actually places the per-expert
        tables on the expert axis."""
        net = MultiLayerNetwork(_moe_conf()).init()
        mesh = mesh_mod.create_mesh((2, 4), axis_names=("data", "expert"))
        ParallelWrapper(net, mesh=mesh, expert_axis="expert")
        spec = net.params_tree["layer_0"]["w1"].sharding.spec
        assert spec[0] == "expert"
        gate_spec = net.params_tree["layer_0"]["gate_w"].sharding.spec
        assert all(s is None for s in gate_spec)

    def test_serde_roundtrip(self):
        conf = _moe_conf(aux_w=0.03, top_k=1, jitter=0.05)
        back = MultiLayerConfiguration.from_json(conf.to_json())
        layer = back.layers[0]
        assert isinstance(layer, MoELayer)
        assert (layer.n_experts, layer.top_k, layer.router_jitter,
                layer.aux_loss_weight) == (4, 1, 0.05, 0.03)


def test_context_cache_key_isolation(rng):
    """The same net trains unsharded, then sharded, then unsharded again —
    the jit cache must not serve a stale sharded program."""
    X, Y = _seq_data(rng)
    net = MultiLayerNetwork(_attention_conf(impl="dense")).init()
    net.fit(DataSet(X, Y))
    mesh = mesh_mod.create_mesh((2, 2), axis_names=("data", "seq"))
    pw = ParallelWrapper(net, mesh=mesh, seq_axis="seq")
    pw.fit(DataSet(X, Y))
    net.fit(DataSet(X, Y))  # back to the unsharded path
    assert np.isfinite(net.score_value)


class TestTransformerLMZoo:
    """zoo.transformer_lm: the DSL-built decoder-only LM (residual
    attention blocks + dense/MoE FFN) trains in both variants and runs
    sequence-sharded through the wrapper unchanged."""

    def _data(self, rng, b=8, t=16, v=20):
        idx = rng.randint(0, v, (b, t))
        X = idx.astype("float32")
        Y = np.eye(v, dtype="float32")[np.roll(idx, -1, axis=1)]
        return X, Y

    @pytest.mark.parametrize("moe", [False, True], ids=["dense", "moe"])
    def test_trains(self, rng, moe):
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        from deeplearning4j_tpu.models.zoo import transformer_lm
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        conf = transformer_lm(vocab_size=20, t=16, d_model=32, n_blocks=2,
                              moe=moe)
        cg = ComputationGraph(conf).init()
        X, Y = self._data(rng)
        mds = MultiDataSet(features=[X], labels=[Y])
        s0 = cg.score(mds)
        for _ in range(25):
            cg.fit(mds)
        assert cg.score(mds) < 0.7 * s0

    def test_seq_sharded_matches_single_device(self, rng):
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        from deeplearning4j_tpu.models.zoo import transformer_lm
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        def make():
            return ComputationGraph(transformer_lm(
                vocab_size=12, t=16, d_model=16, n_heads=2,
                n_blocks=1)).init()

        X, Y = self._data(rng, v=12)
        mds = MultiDataSet(features=[X], labels=[Y])
        cg0 = make()
        for _ in range(4):
            cg0.fit(mds)

        cg1 = make()
        mesh = mesh_mod.create_mesh((2, 2), axis_names=("data", "seq"))
        pw = ParallelWrapper(cg1, mesh=mesh, seq_axis="seq")
        for _ in range(4):
            pw.fit(mds)
        for lk in cg0.params_tree:
            for pk in cg0.params_tree[lk]:
                np.testing.assert_allclose(
                    np.asarray(cg0.params_tree[lk][pk]),
                    np.asarray(cg1.params_tree[lk][pk]),
                    rtol=5e-4, atol=5e-5, err_msg=f"{lk}/{pk}")


def test_ulysses_impl_seq_sharded_matches_single_device(rng):
    """attention_impl='ulysses' routes the seq-sharded path through the
    all-to-all variant; numerics match the single-device run."""
    X, Y = _seq_data(rng)
    net0 = MultiLayerNetwork(_attention_conf(impl="ulysses")).init()
    for _ in range(4):
        net0.fit(DataSet(X, Y))

    net1 = MultiLayerNetwork(_attention_conf(impl="ulysses")).init()
    mesh = mesh_mod.create_mesh((2, 2), axis_names=("data", "seq"))
    pw = ParallelWrapper(net1, mesh=mesh, seq_axis="seq")
    for _ in range(4):
        pw.fit(DataSet(X, Y))
    for lk in net0.params_tree:
        for pk in net0.params_tree[lk]:
            np.testing.assert_allclose(
                np.asarray(net0.params_tree[lk][pk]),
                np.asarray(net1.params_tree[lk][pk]),
                rtol=5e-4, atol=5e-5, err_msg=f"{lk}/{pk}")


def test_generate_lm_samples_learned_pattern(rng):
    """generate_lm continues a trained transformer: on a deterministic
    cyclic corpus, greedy sampling reproduces the cycle."""
    from deeplearning4j_tpu.datasets.dataset import MultiDataSet
    from deeplearning4j_tpu.models.zoo import generate_lm, transformer_lm
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    v, t = 6, 12
    conf = transformer_lm(vocab_size=v, t=t, d_model=32, n_heads=2,
                          n_blocks=1, lr=1e-2)
    cg = ComputationGraph(conf).init()
    # Cyclic sequences 0,1,2,3,4,5,0,1,... from random phases.
    starts = rng.randint(0, v, 16)
    idx = (starts[:, None] + np.arange(t)[None]) % v
    X = idx.astype("float32")
    Y = np.eye(v, dtype="float32")[(idx + 1) % v]
    mds = MultiDataSet(features=[X], labels=[Y])
    for _ in range(150):
        cg.fit(mds)

    out = generate_lm(cg, [2, 3], 6, window=t, temperature=0)
    assert out == [2, 3, 4, 5, 0, 1, 2, 3]


class TestKVCacheDecode:
    """KV-cache stateful decoding: transformer_lm(decode_cache_length=N)
    steps one token at a time via ComputationGraph.rnn_time_step with
    outputs equal to the full forward at every position."""

    def _model(self, rng, v=10, t=12):
        from deeplearning4j_tpu.models.zoo import transformer_lm
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        conf = transformer_lm(vocab_size=v, t=t, d_model=16, n_heads=2,
                              n_blocks=2, decode_cache_length=t)
        return ComputationGraph(conf).init()

    def test_cached_stepping_matches_full_forward(self, rng):
        v, t = 10, 12
        cg = self._model(rng, v, t)
        idx = rng.randint(0, v, (2, t)).astype("float32")
        full = cg.output_single(idx)  # [2, t, v]

        cg.rnn_clear_previous_state()
        prime = cg.rnn_time_step(idx[:, :4, None])[0]  # [2, 4, v]
        np.testing.assert_allclose(prime, full[:, :4], rtol=2e-4, atol=2e-5)
        for pos in range(4, t):
            step = cg.rnn_time_step(idx[:, pos:pos + 1, None])[0]
            np.testing.assert_allclose(
                step[:, 0], full[:, pos], rtol=2e-4, atol=2e-5,
                err_msg=f"position {pos}")

    def test_generate_cached_equals_windowed(self, rng):
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        from deeplearning4j_tpu.models.zoo import generate_lm

        v, t = 6, 16
        cg = self._model(rng, v, t)
        starts = rng.randint(0, v, 16)
        idx = (starts[:, None] + np.arange(t)[None]) % v
        mds = MultiDataSet(features=[idx.astype("float32")],
                           labels=[np.eye(v, dtype="float32")[(idx + 1) % v]])
        for _ in range(120):
            cg.fit(mds)

        windowed = generate_lm(cg, [1, 2], 8, window=t, temperature=0)
        cached = generate_lm(cg, [1, 2], 8, window=t, temperature=0,
                             use_cache=True)
        assert cached == windowed
        assert cached[:6] == [1, 2, 3, 4, 5, 0]

    def test_cache_capacity_guard(self, rng):
        from deeplearning4j_tpu.models.zoo import generate_lm

        cg = self._model(rng, v=6, t=8)
        with pytest.raises(ValueError, match="cache capacity"):
            generate_lm(cg, [1], 20, window=8, temperature=0,
                        use_cache=True)


class TestGraphRnnTimeStep:
    """ComputationGraph.rnn_time_step (reference:
    `ComputationGraph.rnnTimeStep:1386`): stepping one timestep at a time
    with carried hidden state equals the full-sequence forward."""

    def test_lstm_graph_stepping_matches_full(self, rng):
        from deeplearning4j_tpu.nn.conf.layers import GravesLSTM
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        gb = (NeuralNetConfiguration.builder()
              .seed(3).learning_rate(0.1).updater("sgd")
              .graph_builder()
              .add_inputs("in")
              .add_layer("lstm", GravesLSTM(n_out=8, activation="tanh"), "in")
              .add_layer("out", RnnOutputLayer(n_out=3, activation="softmax",
                                               loss_function="mcxent"),
                         "lstm")
              .set_outputs("out"))
        gb.set_input_types(InputType.recurrent(4, 6))
        cg = ComputationGraph(gb.build()).init()
        X = rng.randn(2, 6, 4).astype("float32")
        full = cg.output_single(X)

        cg.rnn_clear_previous_state()
        steps = [cg.rnn_time_step(X[:, t])[0] for t in range(6)]
        np.testing.assert_allclose(np.stack(steps, axis=1), full,
                                   rtol=1e-5, atol=1e-6)
        # Clearing state restarts the sequence.
        cg.rnn_clear_previous_state()
        again = cg.rnn_time_step(X[:, 0])[0]
        np.testing.assert_allclose(again, steps[0], rtol=1e-6)


class TestKVCacheGuards:
    """Review-driven guards: plain forward works past the cache size,
    decode overflow fails fast host-side, n_steps=0 parity, tBPTT rejects
    cached models, stateless positional default."""

    def test_plain_forward_beyond_cache_length(self, rng):
        from deeplearning4j_tpu.models.zoo import transformer_lm
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        conf = transformer_lm(vocab_size=6, t=8, d_model=16, n_heads=2,
                              n_blocks=1, decode_cache_length=4)
        cg = ComputationGraph(conf).init()
        out = cg.output_single(rng.randint(0, 6, (2, 8)).astype("float32"))
        assert out.shape == (2, 8, 6) and np.isfinite(out).all()

    def test_decode_overflow_raises(self, rng):
        from deeplearning4j_tpu.models.zoo import transformer_lm
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        conf = transformer_lm(vocab_size=6, t=8, d_model=16, n_heads=2,
                              n_blocks=1, decode_cache_length=4)
        cg = ComputationGraph(conf).init()
        cg.rnn_clear_previous_state()
        x = rng.randint(0, 6, (1, 3, 1)).astype("float32")
        cg.rnn_time_step(x)
        with pytest.raises(ValueError, match="decode cache capacity"):
            cg.rnn_time_step(rng.randint(0, 6, (1, 2, 1)).astype("float32"))
        cg.rnn_clear_previous_state()  # resets the budget
        cg.rnn_time_step(x)

    def test_generate_zero_steps_parity(self, rng):
        from deeplearning4j_tpu.models.zoo import generate_lm, transformer_lm
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        conf = transformer_lm(vocab_size=6, t=8, d_model=16, n_heads=2,
                              n_blocks=1, decode_cache_length=8)
        cg = ComputationGraph(conf).init()
        assert generate_lm(cg, [1, 2], 0, window=8) == [1, 2]
        assert generate_lm(cg, [1, 2], 0, window=8, use_cache=True) == [1, 2]

    def test_tbptt_rejects_cached_model(self, rng):
        from deeplearning4j_tpu.nn.conf.layers import (
            RnnOutputLayer, SelfAttentionLayer,
        )

        conf = (NeuralNetConfiguration.builder()
                .seed(1).learning_rate(0.1).updater("sgd")
                .list()
                .layer(SelfAttentionLayer(n_out=8, n_heads=2,
                                          decode_cache_length=16))
                .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                      loss_function="mcxent"))
                .backprop_type("truncatedbptt")
                .t_bptt_forward_length(4).t_bptt_backward_length(4)
                .set_input_type(InputType.recurrent(8, 12))
                .build())
        net = MultiLayerNetwork(conf).init()
        X = rng.randn(2, 12, 8).astype("float32")
        Y = np.eye(3)[rng.randint(0, 3, (2, 12))].astype("float32")
        with pytest.raises(ValueError, match="truncated BPTT"):
            net.fit(DataSet(X, Y))

    def test_positional_default_is_stateless(self, rng):
        """Without stateful=True the positional layer ignores carried
        state — pre-round-5 semantics for every existing model."""
        from deeplearning4j_tpu.nn.conf.layers import (
            PositionalEmbeddingLayer, RnnOutputLayer,
        )

        conf = (_builder().list()
                .layer(PositionalEmbeddingLayer(max_length=8))
                .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                      loss_function="mcxent"))
                .set_input_type(InputType.recurrent(4, 4))
                .build())
        net = MultiLayerNetwork(conf).init()
        X = rng.randn(2, 4, 4).astype("float32")
        net.rnn_clear_previous_state()
        a = net.rnn_time_step(X)
        b = net.rnn_time_step(X)  # cursor must NOT advance
        np.testing.assert_allclose(a, b, rtol=1e-6)


class TestAxisComposition:
    """Parallel axes compose through ONE DSL model: attention under
    tensor parallelism, and a 3-axis data x seq x expert mesh driving
    ring attention and expert-parallel MoE in the same jitted step."""

    def test_attention_with_tensor_parallel(self, rng):
        X, Y = _seq_data(rng)
        net0 = MultiLayerNetwork(_attention_conf(impl="dense")).init()
        for _ in range(4):
            net0.fit(DataSet(X, Y))

        net1 = MultiLayerNetwork(_attention_conf(impl="dense")).init()
        mesh = mesh_mod.create_mesh((2, 4), axis_names=("data", "model"))
        pw = ParallelWrapper(net1, mesh=mesh, model_axis="model")
        for _ in range(4):
            pw.fit(DataSet(X, Y))
        for lk in net0.params_tree:
            for pk in net0.params_tree[lk]:
                np.testing.assert_allclose(
                    np.asarray(net0.params_tree[lk][pk]),
                    np.asarray(net1.params_tree[lk][pk]),
                    rtol=5e-4, atol=5e-5, err_msg=f"{lk}/{pk}")

    def test_three_axis_mesh_attention_plus_moe(self, rng):
        def make():
            conf = (_builder().list()
                    .layer(SelfAttentionLayer(n_out=16, n_heads=4,
                                              causal=True))
                    .layer(MoELayer(n_out=16, n_experts=2, expert_hidden=32,
                                    top_k=2))
                    .layer(RnnOutputLayer(n_out=5, activation="softmax",
                                          loss_function="mcxent"))
                    .set_input_type(InputType.recurrent(8, 12))
                    .build())
            return MultiLayerNetwork(conf).init()

        X, Y = _seq_data(rng)
        net0 = make()
        net0.fit(DataSet(X, Y))

        net1 = make()
        mesh = mesh_mod.create_mesh((2, 2, 2),
                                    axis_names=("data", "seq", "expert"))
        pw = ParallelWrapper(net1, mesh=mesh, seq_axis="seq",
                             expert_axis="expert")
        pw.fit(DataSet(X, Y))
        assert net1.params_tree["layer_1"]["w1"].sharding.spec[0] == "expert"
        for lk in net0.params_tree:
            for pk in net0.params_tree[lk]:
                np.testing.assert_allclose(
                    np.asarray(net0.params_tree[lk][pk]),
                    np.asarray(net1.params_tree[lk][pk]),
                    rtol=5e-4, atol=5e-5, err_msg=f"{lk}/{pk}")


def test_generate_top_k_restricts_support(rng):
    """top_k=1 sampling == greedy; top_k bounds the sampled support."""
    from deeplearning4j_tpu.models.zoo import generate_lm, transformer_lm
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    cg = ComputationGraph(transformer_lm(
        vocab_size=8, t=8, d_model=16, n_heads=2, n_blocks=1)).init()
    greedy = generate_lm(cg, [1], 5, window=8, temperature=0)
    k1 = generate_lm(cg, [1], 5, window=8, temperature=1.0, top_k=1)
    assert k1 == greedy
    # top_k=2: every sampled token is one of the 2 best at its position
    out = generate_lm(cg, [1], 5, window=8, temperature=1.0, top_k=2,
                      seed=7)
    assert len(out) == 6


def test_transformer_checkpoint_roundtrip(rng, tmp_path):
    """save_model/load_model over a transformer graph (SelfAttention + MoE
    + LayerNorm + positional layers): the zip format that failure-recovery
    rollback depends on must cover the round-5 layer types."""
    from deeplearning4j_tpu.datasets.dataset import MultiDataSet
    from deeplearning4j_tpu.models.zoo import transformer_lm
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.util.model_serializer import (
        load_model, save_model,
    )

    v, t = 8, 12
    cg = ComputationGraph(transformer_lm(
        vocab_size=v, t=t, d_model=16, n_heads=2, n_blocks=1,
        moe=True, decode_cache_length=t)).init()
    idx = rng.randint(0, v, (4, t))
    mds = MultiDataSet(features=[idx.astype("float32")],
                       labels=[np.roll(idx, -1, axis=1).astype(np.int32)])
    for _ in range(3):
        cg.fit(mds)

    path = str(tmp_path / "tf.zip")
    save_model(cg, path)
    back = load_model(path)
    x = idx.astype("float32")
    np.testing.assert_allclose(back.output_single(x), cg.output_single(x),
                               rtol=1e-5, atol=1e-6)
    # The restored model keeps training and decoding.
    back.fit(mds)
    assert np.isfinite(back.score_value)
    from deeplearning4j_tpu.models.zoo import generate_lm
    out = generate_lm(back, [1], 3, window=t, temperature=0, use_cache=True)
    assert len(out) == 4


def test_lbfgs_solver_over_attention(rng):
    """The full-batch solver path (LBFGS as one jitted loop) composes with
    the attention layer."""
    conf = (NeuralNetConfiguration.builder()
            .seed(4).learning_rate(0.1)
            .optimization_algo("lbfgs").iterations(5)
            .list()
            .layer(SelfAttentionLayer(n_out=8, n_heads=2, causal=True,
                                      attention_impl="dense"))
            .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.recurrent(4, 6))
            .build())
    net = MultiLayerNetwork(conf).init()
    X = rng.randn(6, 6, 4).astype("float32")
    Y = np.eye(3)[rng.randint(0, 3, (6, 6))].astype("float32")
    s0 = net.score(DataSet(X, Y))
    net.fit(DataSet(X, Y))
    assert net.score(DataSet(X, Y)) < s0


def test_transformer_classifier_learns_with_masks(rng):
    """zoo.transformer_classifier: bidirectional encoder + mean pool
    classifies ragged token sequences (class = which token dominates),
    with feature masks excluding the padding."""
    from deeplearning4j_tpu.datasets.dataset import MultiDataSet
    from deeplearning4j_tpu.models.zoo import transformer_classifier
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    v, t, c = 12, 16, 3
    cg = ComputationGraph(transformer_classifier(
        vocab_size=v, n_classes=c, t=t, d_model=32, n_heads=4,
        n_blocks=1, lr=5e-3)).init()
    n = 48
    cls = rng.randint(0, c, n)
    lens = rng.randint(6, t + 1, n)
    idx = rng.randint(0, v, (n, t))
    mask = np.zeros((n, t), np.float32)
    for i in range(n):
        mask[i, :lens[i]] = 1.0
        # make ~60% of the VALID tokens the class-identifying token
        sel = rng.rand(lens[i]) < 0.6
        idx[i, :lens[i]][sel] = cls[i]
        idx[i, lens[i]:] = 0  # padding garbage the mask must hide
    mds = MultiDataSet(features=[idx.astype("float32")],
                       labels=[cls.astype(np.int32)],
                       features_masks=[mask])
    s0 = cg.score(mds)
    for _ in range(60):
        cg.fit(mds)
    assert cg.score(mds) < 0.5 * s0
    out = cg.output_single(idx.astype("float32"), features_masks=[mask])
    acc = (out.argmax(-1) == cls).mean()
    assert acc > 0.85, acc


def test_early_stopping_with_transformer_graph(rng, tmp_path):
    """EarlyStoppingTrainer drives a ComputationGraph transformer: score
    calculators and savers are engine-agnostic (fit/score surface)."""
    from deeplearning4j_tpu.datasets.dataset import MultiDataSet
    from deeplearning4j_tpu.earlystopping import (
        EarlyStoppingConfiguration, EarlyStoppingTrainer,
        LocalFileModelSaver, MaxEpochsTerminationCondition,
    )
    from deeplearning4j_tpu.earlystopping.scorecalc import (
        DataSetLossCalculator,
    )
    from deeplearning4j_tpu.models.zoo import transformer_lm
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    v, t = 8, 10
    cg = ComputationGraph(transformer_lm(
        vocab_size=v, t=t, d_model=16, n_heads=2, n_blocks=1)).init()
    idx = rng.randint(0, v, (8, t))
    mds = MultiDataSet(features=[idx.astype("float32")],
                       labels=[np.roll(idx, -1, axis=1).astype(np.int32)])
    conf = EarlyStoppingConfiguration(
        epoch_termination_conditions=[MaxEpochsTerminationCondition(4)],
        score_calculator=DataSetLossCalculator([mds]),
        model_saver=LocalFileModelSaver(str(tmp_path)),
    )
    result = EarlyStoppingTrainer(conf, cg, [mds]).fit()
    assert result.total_epochs >= 1
    assert np.isfinite(result.best_model_score)


def test_classifier_t_equals_vocab_unambiguous(rng):
    """input_format='ids' pins the embedding interpretation: a [n, t] float
    id matrix with t == vocab_size must NOT be misread as one-hot."""
    from deeplearning4j_tpu.datasets.dataset import MultiDataSet
    from deeplearning4j_tpu.models.zoo import transformer_classifier
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    v = t = 12  # the ambiguous shape
    cg = ComputationGraph(transformer_classifier(
        vocab_size=v, n_classes=2, t=t, d_model=16, n_heads=2,
        n_blocks=1)).init()
    idx = rng.randint(0, v, (4, t)).astype("float32")
    out = cg.output_single(idx)
    assert out.shape == (4, 2)
    # Changing a token must change the logits (one-hot misread would
    # collapse each row to argmax-over-time, often ignoring this edit).
    idx2 = idx.copy()
    idx2[0, 3] = (idx2[0, 3] + 1) % v
    out2 = cg.output_single(idx2)
    assert not np.allclose(out[0], out2[0])


def test_generate_lm_batch_matches_per_prompt(rng):
    """Batched KV-cached decode == per-prompt decode, row for row
    (greedy; the whole batch shares each single-token dispatch)."""
    from deeplearning4j_tpu.datasets.dataset import MultiDataSet
    from deeplearning4j_tpu.models.zoo import (
        generate_lm, generate_lm_batch, transformer_lm,
    )
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    v, t = 6, 16
    cg = ComputationGraph(transformer_lm(
        vocab_size=v, t=t, d_model=16, n_heads=2, n_blocks=1,
        decode_cache_length=t)).init()
    starts = rng.randint(0, v, 16)
    idx = (starts[:, None] + np.arange(t)[None]) % v
    mds = MultiDataSet(features=[idx.astype("float32")],
                       labels=[np.roll(idx, -1, axis=1).astype(np.int32)])
    for _ in range(120):
        cg.fit(mds)

    prompts = np.asarray([[1, 2], [4, 5], [0, 1]])
    batch = generate_lm_batch(cg, prompts, 6, temperature=0)
    assert batch.shape == (3, 8)
    for i, p in enumerate(prompts):
        single = generate_lm(cg, list(p), 6, window=t, temperature=0,
                             use_cache=True)
        assert batch[i].tolist() == single, f"row {i}"
    with pytest.raises(ValueError, match="cache capacity"):
        generate_lm_batch(cg, prompts, 30)
