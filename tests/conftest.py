"""Test configuration.

Tests run on an 8-device virtual CPU mesh (the reference runs its suite
against a pluggable nd4j backend via Maven profiles, SURVEY.md §4; the TPU
analog is XLA's host-platform device-count simulation) with x64 enabled so
gradient checks run in double precision.
"""

import os
import tempfile

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# Hermetic compile cache: without this, tests would share (and pollute)
# the developer's per-user cache dir, and cached executables from an
# earlier run would turn expected compiles into AOT hits.
os.environ.setdefault("DL4J_TPU_COMPILE_CACHE",
                      tempfile.mkdtemp(prefix="dl4j-test-compile-cache-"))

import jax

# The image's sitecustomize pre-imports jax pinned to the tunneled TPU
# (JAX_PLATFORMS=axon); config.update is the override that sticks.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np
import pytest

if len(jax.devices()) < 8:
    pytest.exit(
        f"Tests need >=8 virtual CPU devices (got {len(jax.devices())}). "
        "Unset any conflicting --xla_force_host_platform_device_count in XLA_FLAGS.",
        returncode=3,
    )


@pytest.fixture
def rng():
    return np.random.RandomState(12345)


def make_classification_data(rng, n=64, n_features=4, n_classes=3, dtype="float64"):
    X = rng.randn(n, n_features).astype(dtype)
    W = rng.randn(n_features, n_classes)
    y_idx = np.argmax(X @ W + 0.1 * rng.randn(n, n_classes), axis=1)
    Y = np.eye(n_classes)[y_idx].astype(dtype)
    return X, Y
