"""Seeded configuration fuzz: random (valid) layer stacks built through
the DSL must init, fit one step, and infer — across layer-type
combinations no hand-written test enumerates (reference analog: the
breadth of `MultiLayerTest`/`GradientCheckTests` matrices, generated).

Deterministic: every config derives from a fixed seed, so a failure
reproduces by its index.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer,
    BatchNormalization,
    DenseLayer,
    DropoutLayer,
    GlobalPoolingLayer,
    GravesLSTM,
    LayerNormalization,
    LSTM,
    MoELayer,
    OutputLayer,
    RnnOutputLayer,
    SelfAttentionLayer,
    SimpleRnn,
)
from deeplearning4j_tpu.nn.conf.neural_net import (
    MultiLayerConfiguration,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

ACTS = ["relu", "tanh", "sigmoid", "elu", "softplus"]


def _random_stack(rng):
    """A random valid MLN: ff or rnn input, 2-4 hidden layers drawn from
    the pool with adjacency rules, matching output layer."""
    rnn = bool(rng.randint(2))
    width = int(rng.choice([8, 12, 16]))
    layers = []
    kind = "rnn" if rnn else "ff"
    for _ in range(rng.randint(2, 5)):
        if kind == "rnn":
            choice = rng.choice(
                ["dense", "lstm", "graves", "simple", "attn", "moe",
                 "ln", "bn", "act", "drop", "pool"])
        else:
            choice = rng.choice(["dense", "ln", "bn", "act", "drop"])
        act = str(rng.choice(ACTS))
        if choice == "dense":
            layers.append(DenseLayer(n_out=width, activation=act))
        elif choice == "lstm":
            layers.append(LSTM(n_out=width, activation="tanh"))
        elif choice == "graves":
            layers.append(GravesLSTM(n_out=width, activation="tanh"))
        elif choice == "simple":
            layers.append(SimpleRnn(n_out=width, activation="tanh"))
        elif choice == "attn":
            layers.append(SelfAttentionLayer(
                n_out=width, n_heads=int(rng.choice([2, 4])),
                causal=bool(rng.randint(2)), attention_impl="dense"))
        elif choice == "moe":
            layers.append(MoELayer(n_out=width, n_experts=2,
                                   expert_hidden=2 * width,
                                   top_k=int(rng.choice([1, 2]))))
        elif choice == "ln":
            layers.append(LayerNormalization())
        elif choice == "bn":
            layers.append(BatchNormalization())
        elif choice == "act":
            layers.append(ActivationLayer(activation=act))
        elif choice == "drop":
            layers.append(DropoutLayer(dropout=0.8))
        elif choice == "pool":
            layers.append(GlobalPoolingLayer(
                pooling_type=str(rng.choice(["max", "avg", "sum"]))))
            kind = "ff"  # pooling collapses time
    n_classes = 3
    if kind == "rnn":
        layers.append(RnnOutputLayer(n_out=n_classes, activation="softmax",
                                     loss_function="mcxent"))
    else:
        layers.append(OutputLayer(n_out=n_classes, activation="softmax",
                                  loss_function="mcxent"))
    return rnn, kind, width, layers, n_classes


@pytest.mark.parametrize("i", range(24))
def test_random_config(i):
    rng = np.random.RandomState(1000 + i)
    rnn, out_kind, width, layers, n_classes = _random_stack(rng)
    f, t, b = 6, 8, 4
    builder = (NeuralNetConfiguration.builder()
               .seed(int(rng.randint(1 << 16)))
               .learning_rate(0.05)
               .updater(str(rng.choice(["sgd", "adam", "rmsprop"])))
               .list())
    for l in layers:
        builder = builder.layer(l)
    conf = builder.set_input_type(
        InputType.recurrent(f, t) if rnn else InputType.feed_forward(f)
    ).build()

    # JSON round-trip must hold for every generated config.
    back = MultiLayerConfiguration.from_json(conf.to_json())
    assert back.to_json() == conf.to_json(), f"config {i} JSON drift"

    net = MultiLayerNetwork(conf).init()
    X = rng.randn(b, t, f).astype("float32") if rnn \
        else rng.randn(b, f).astype("float32")
    if out_kind == "rnn":
        Y = np.eye(n_classes)[rng.randint(0, n_classes,
                                          (b, t))].astype("float32")
    else:
        Y = np.eye(n_classes)[rng.randint(0, n_classes, b)].astype("float32")
    net.fit(DataSet(X, Y))
    assert np.isfinite(net.score_value), f"config {i} non-finite loss"
    out = net.output(X)
    assert np.isfinite(out).all(), f"config {i} non-finite output"
