"""Seeded configuration fuzz: random (valid) layer stacks built through
the DSL must init, fit one step, and infer — across layer-type
combinations no hand-written test enumerates (reference analog: the
breadth of `MultiLayerTest`/`GradientCheckTests` matrices, generated).

Deterministic: every config derives from a fixed seed, so a failure
reproduces by its index.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer,
    BatchNormalization,
    DenseLayer,
    DropoutLayer,
    GlobalPoolingLayer,
    GravesLSTM,
    LayerNormalization,
    LSTM,
    MoELayer,
    OutputLayer,
    RnnOutputLayer,
    SelfAttentionLayer,
    SimpleRnn,
)
from deeplearning4j_tpu.nn.conf.neural_net import (
    MultiLayerConfiguration,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

ACTS = ["relu", "tanh", "sigmoid", "elu", "softplus"]


def _random_stack(rng):
    """A random valid MLN: ff or rnn input, 2-4 hidden layers drawn from
    the pool with adjacency rules, matching output layer."""
    rnn = bool(rng.randint(2))
    width = int(rng.choice([8, 12, 16]))
    layers = []
    kind = "rnn" if rnn else "ff"
    for _ in range(rng.randint(2, 5)):
        if kind == "rnn":
            choice = rng.choice(
                ["dense", "lstm", "graves", "simple", "attn", "moe",
                 "ln", "bn", "act", "drop", "pool"])
        else:
            choice = rng.choice(["dense", "ln", "bn", "act", "drop"])
        act = str(rng.choice(ACTS))
        if choice == "dense":
            layers.append(DenseLayer(n_out=width, activation=act))
        elif choice == "lstm":
            layers.append(LSTM(n_out=width, activation="tanh"))
        elif choice == "graves":
            layers.append(GravesLSTM(n_out=width, activation="tanh"))
        elif choice == "simple":
            layers.append(SimpleRnn(n_out=width, activation="tanh"))
        elif choice == "attn":
            layers.append(SelfAttentionLayer(
                n_out=width, n_heads=int(rng.choice([2, 4])),
                causal=bool(rng.randint(2)), attention_impl="dense"))
        elif choice == "moe":
            layers.append(MoELayer(n_out=width, n_experts=2,
                                   expert_hidden=2 * width,
                                   top_k=int(rng.choice([1, 2]))))
        elif choice == "ln":
            layers.append(LayerNormalization())
        elif choice == "bn":
            layers.append(BatchNormalization())
        elif choice == "act":
            layers.append(ActivationLayer(activation=act))
        elif choice == "drop":
            layers.append(DropoutLayer(dropout=0.8))
        elif choice == "pool":
            layers.append(GlobalPoolingLayer(
                pooling_type=str(rng.choice(["max", "avg", "sum"]))))
            kind = "ff"  # pooling collapses time
    n_classes = 3
    if kind == "rnn":
        layers.append(RnnOutputLayer(n_out=n_classes, activation="softmax",
                                     loss_function="mcxent"))
    else:
        layers.append(OutputLayer(n_out=n_classes, activation="softmax",
                                  loss_function="mcxent"))
    return rnn, kind, width, layers, n_classes


@pytest.mark.parametrize("i", range(24))
def test_random_config(i):
    rng = np.random.RandomState(1000 + i)
    rnn, out_kind, width, layers, n_classes = _random_stack(rng)
    f, t, b = 6, 8, 4
    builder = (NeuralNetConfiguration.builder()
               .seed(int(rng.randint(1 << 16)))
               .learning_rate(0.05)
               .updater(str(rng.choice(["sgd", "adam", "rmsprop"])))
               .list())
    for l in layers:
        builder = builder.layer(l)
    conf = builder.set_input_type(
        InputType.recurrent(f, t) if rnn else InputType.feed_forward(f)
    ).build()

    # JSON round-trip must hold for every generated config.
    back = MultiLayerConfiguration.from_json(conf.to_json())
    assert back.to_json() == conf.to_json(), f"config {i} JSON drift"

    net = MultiLayerNetwork(conf).init()
    X = rng.randn(b, t, f).astype("float32") if rnn \
        else rng.randn(b, f).astype("float32")
    if out_kind == "rnn":
        Y = np.eye(n_classes)[rng.randint(0, n_classes,
                                          (b, t))].astype("float32")
    else:
        Y = np.eye(n_classes)[rng.randint(0, n_classes, b)].astype("float32")
    net.fit(DataSet(X, Y))
    assert np.isfinite(net.score_value), f"config {i} non-finite loss"
    out = net.output(X)
    assert np.isfinite(out).all(), f"config {i} non-finite output"


@pytest.mark.parametrize("i", range(12))
def test_random_graph_topology(i):
    """Random DAGs: 1-2 inputs, branch + merge/elementwise vertices,
    random layer types at the nodes — build, validate, train a step."""
    from deeplearning4j_tpu.datasets.dataset import MultiDataSet
    from deeplearning4j_tpu.nn.conf.graph import (
        ElementWiseVertex, MergeVertex,
    )
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    rng = np.random.RandomState(2000 + i)
    f, b, width = 5, 4, 8
    n_inputs = int(rng.randint(1, 3))
    inputs = [f"in{k}" for k in range(n_inputs)]
    gb = (NeuralNetConfiguration.builder()
          .seed(int(rng.randint(1 << 16))).learning_rate(0.05)
          .updater(str(rng.choice(["sgd", "adam"])))
          .graph_builder()
          .add_inputs(*inputs))
    # Every node: a dense layer on 1-2 existing nodes (merged or summed).
    nodes = list(inputs)
    widths = {n: f for n in inputs}
    for j in range(rng.randint(2, 6)):
        k = int(rng.randint(1, 3))
        srcs = [nodes[int(rng.randint(len(nodes)))] for _ in range(k)]
        if len(srcs) == 2:
            if widths[srcs[0]] == widths[srcs[1]] and rng.randint(2):
                vname = f"ew{j}"
                gb.add_vertex(vname, ElementWiseVertex(op="add"), *srcs)
                widths[vname] = widths[srcs[0]]
            else:
                vname = f"mg{j}"
                gb.add_vertex(vname, MergeVertex(), *srcs)
                widths[vname] = widths[srcs[0]] + widths[srcs[1]]
            src = vname
            nodes.append(vname)
        else:
            src = srcs[0]
        lname = f"d{j}"
        gb.add_layer(lname, DenseLayer(n_out=width,
                                       activation=str(rng.choice(ACTS))),
                     src)
        widths[lname] = width
        nodes.append(lname)
    head_src = nodes[-1]
    gb.add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                    loss_function="mcxent"), head_src)
    gb.set_outputs("out")
    gb.set_input_types(*[InputType.feed_forward(f)] * n_inputs)
    cg = ComputationGraph(gb.build()).init()

    X = [rng.randn(b, f).astype("float32") for _ in range(n_inputs)]
    Y = np.eye(3)[rng.randint(0, 3, b)].astype("float32")
    cg.fit(MultiDataSet(features=X, labels=[Y]))
    assert np.isfinite(cg.score_value), f"graph {i} non-finite loss"
    out = cg.output_single(*X)
    assert np.isfinite(out).all(), f"graph {i} non-finite output"
