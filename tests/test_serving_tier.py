"""Production serving tier (`deeplearning4j_tpu/serving/`).

Acceptance coverage for the serving-tier PR:

- continuous-batched generation is float-close (here: exactly equal,
  greedy and seeded) to the sequential `generate_lm(use_cache=True)` path,
  including under concurrent interleaved admission and slot recycling;
- admission is bounded and observable: full queues shed with 503 +
  `Retry-After`, expired/abandoned requests are DROPPED before the device
  sees them and counted under `dl4j_requests_total{outcome="timeout"}`;
- the token-ids dtype policy: ids models never round-trip through
  float32, fractional floats are a 400;
- cross-process zero-compile: a fresh process serving 2 models over a
  >= 3-bucket ladder from a warmed AOT store performs ZERO XLA compiles;
- multi-model hosting: per-model routing, HBM gauges, LRU eviction under
  a budget and transparent reload;
- one `/metrics` scrape carries the per-model SLO families.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import (MultiLayerNetwork, NeuralNetConfiguration,
                                observability as obs)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.serving import (
    InferenceServer,
    InputValidationError,
    ServerOverloadedError,
    ShapeBucketBatcher,
    bucket_ladder,
    prompt_bucket_ladder,
)


def mlp_net(seed=1, n_in=3, n_out=2):
    return MultiLayerNetwork(
        (NeuralNetConfiguration.builder()
         .seed(seed).learning_rate(0.1).weight_init("xavier")
         .list()
         .layer(DenseLayer(n_out=4, activation="tanh"))
         .layer(OutputLayer(n_out=n_out, activation="softmax",
                            loss_function="mcxent"))
         .set_input_type(InputType.feed_forward(n_in))
         .build())).init()


def _counter_total(name, **match):
    fam = obs.metrics.get_family(name)
    if fam is None:
        return 0.0
    return sum(c.get() for c in fam.children()
               if all(c.labels.get(k) == v for k, v in match.items()))


@pytest.fixture(scope="module")
def lm():
    from deeplearning4j_tpu.models import zoo
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    conf = zoo.transformer_lm(vocab_size=17, t=16, d_model=16, n_heads=2,
                              n_blocks=1, decode_cache_length=32)
    return ComputationGraph(conf).init()


# ------------------------------------------------------ continuous batching


class TestContinuousGeneration:
    def test_greedy_and_seeded_match_sequential(self, lm):
        from deeplearning4j_tpu.models import zoo

        server = InferenceServer(lm, decode_slots=3)
        try:
            ref = zoo.generate_lm(lm, [1, 2, 3], 6, window=16,
                                  use_cache=True, temperature=0.0)
            assert server.generate([1, 2, 3], 6, temperature=0.0) == ref
            ref = zoo.generate_lm(lm, [4, 5], 5, window=16, use_cache=True,
                                  temperature=0.8, seed=7, top_k=5)
            assert server.generate([4, 5], 5, temperature=0.8, seed=7,
                                   top_k=5) == ref
        finally:
            server.stop()

    def test_concurrent_interleaved_and_slot_recycling(self, lm):
        """More concurrent generations than decode slots: sequences join
        mid-flight at step boundaries and recycle slots on completion —
        every result still exactly matches the sequential path."""
        from deeplearning4j_tpu.models import zoo

        server = InferenceServer(lm, decode_slots=2)
        try:
            results, errors = {}, []

            def run(i):
                try:
                    results[i] = server.generate([1 + i, 2 + i], 4 + i % 3,
                                                 temperature=0.0)
                except Exception as e:  # pragma: no cover - diagnostic
                    errors.append(e)

            threads = [threading.Thread(target=run, args=(i,))
                       for i in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errors
            for i in range(6):
                ref = zoo.generate_lm(lm, [1 + i, 2 + i], 4 + i % 3,
                                      window=16, use_cache=True,
                                      temperature=0.0)
                assert results[i] == ref
        finally:
            server.stop()

    def test_drain_mode_matches_too(self, lm):
        from deeplearning4j_tpu.models import zoo

        server = InferenceServer(lm, decode_slots=2,
                                 scheduler_mode="drain")
        try:
            ref = zoo.generate_lm(lm, [3, 1], 5, window=16, use_cache=True,
                                  temperature=0.0)
            assert server.generate([3, 1], 5, temperature=0.0) == ref
        finally:
            server.stop()

    def test_capacity_and_deadline(self, lm):
        server = InferenceServer(lm, decode_slots=2)
        try:
            with pytest.raises(InputValidationError):
                server.generate([1] * 30, 10, temperature=0.0)
            # A deadline far shorter than prefill+decode: the request is
            # retired at a step boundary and surfaces as a timeout...
            with pytest.raises(TimeoutError):
                server.generate([1, 2], 28, temperature=0.0,
                                timeout_s=0.001)
            # ...and the slot is recycled — the next generation succeeds.
            out = server.generate([2, 3], 3, temperature=0.0)
            assert len(out) == 5
        finally:
            server.stop()


# --------------------------------------------------------------- admission


class _CountingNet:
    """Records each executed batch's row count."""

    def __init__(self, n_out=2):
        self.n_out = n_out
        self.batches = []

    def output(self, x):
        x = np.asarray(x)
        self.batches.append(x.shape[0])
        return np.zeros((x.shape[0], self.n_out), np.float32)


class TestAdmission:
    def test_bucket_ladders(self):
        assert bucket_ladder(32) == (1, 2, 4, 8, 16, 32)
        assert bucket_ladder(12, buckets=(4, 8)) == (4, 8, 12)
        assert prompt_bucket_ladder(64) == (8, 16, 32, 64)
        assert prompt_bucket_ladder(24, buckets=(8,)) == (8, 24)

    def test_full_queue_sheds_503(self):
        net = _CountingNet()
        batcher = ShapeBucketBatcher(net, model_name="shed-test",
                                     max_batch_size=2, queue_depth=2,
                                     warmup_shape=(3,))
        # Loop never started: the queue can only fill.
        row = np.zeros((1, 3), np.float32)
        batcher.submit(row, None)
        batcher.submit(row, None)
        with pytest.raises(ServerOverloadedError) as e:
            batcher.submit(row, None)
        assert e.value.status == 503
        assert e.value.retry_after == 1

    def test_http_shed_has_retry_after(self):
        server = InferenceServer(_CountingNet(), port=0, queue_depth=1,
                                 warmup_shape=(3,)).start()
        try:
            served = server.models.get(server.default_model)
            served.batcher.stop()  # freeze the drain so the queue fills
            time.sleep(0.05)
            served.batcher.submit(np.zeros((1, 3), np.float32), None)
            req = urllib.request.Request(
                server.url + "/predict",
                data=json.dumps({"data": [[0.0, 0.0, 0.0]]}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=10)
            assert e.value.code == 503
            assert e.value.headers.get("Retry-After") == "1"
        finally:
            server.stop()

    def test_cancelled_and_expired_dropped_before_device(self):
        """The timeout-abandonment fix: a request whose caller gave up (or
        whose deadline passed in the queue) is dropped at batch-build time
        — the model never executes it — and counted as a timeout."""
        net = _CountingNet()
        batcher = ShapeBucketBatcher(net, model_name="drop-test",
                                     max_batch_size=4, warmup_shape=(3,))
        before = _counter_total("dl4j_requests_total", model="drop-test",
                                outcome="timeout")
        abandoned = batcher.submit(np.zeros((1, 3), np.float32), None)
        abandoned.cancelled = True
        expired = batcher.submit(np.zeros((1, 3), np.float32),
                                 time.monotonic() - 1.0)
        live = batcher.submit(np.zeros((2, 3), np.float32), None)
        batcher._run_batch([abandoned, expired, live])
        assert net.batches == [2]  # only the live rows ran (bucket 2)
        assert live.result is not None
        assert abandoned.event.is_set() and expired.event.is_set()
        assert expired.error == "__deadline__"
        after = _counter_total("dl4j_requests_total", model="drop-test",
                               outcome="timeout")
        assert after == before + 2

    def test_caller_timeout_cancels_and_next_batch_skips(self):
        """End-to-end: A occupies the (slow) device, B's caller times out
        while queued; when the loop builds the next batch it drops B."""
        class Slow(_CountingNet):
            def output(self, x):
                time.sleep(0.25)
                return super().output(x)

        net = Slow()
        server = InferenceServer(net, max_delay_ms=1.0, warmup_shape=(3,))
        try:
            row = [[0.0, 0.0, 0.0]]
            a = threading.Thread(target=server.predict, args=(row,))
            a.start()
            time.sleep(0.05)  # A's batch is executing
            with pytest.raises(TimeoutError) as e:
                server.predict(row, timeout_s=0.05)
            assert "predict_timeout_s" in str(e.value)
            a.join()
            time.sleep(0.4)  # let the loop drain the cancelled entry
            assert net.batches == [1]  # B never reached the model
        finally:
            server.stop()

    def test_concurrent_predicts_all_complete(self):
        net = mlp_net()
        server = InferenceServer(net, max_batch_size=4, max_delay_ms=2.0)
        try:
            X = np.random.RandomState(0).rand(12, 3).astype(np.float32)
            full = np.asarray(net.output(X))
            results = {}

            def call(i):
                results[i] = server.predict(X[i:i + 1])

            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(12)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for i, p in results.items():
                np.testing.assert_allclose(p[0], full[i], rtol=1e-5,
                                           atol=1e-6)
        finally:
            server.stop()


# ------------------------------------------------------------ dtype policy


class TestInputDtypePolicy:
    def test_ids_model_keeps_integer_precision(self, lm):
        server = InferenceServer(lm, max_batch_size=4)
        try:
            ids = np.array([[1, 2, 3, 4, 5, 6, 7, 8]], np.int64)
            out = server.predict(ids)
            ref = np.asarray(
                lm.output(ids.astype(np.float32)[..., None])[0])
            np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
        finally:
            server.stop()

    def test_fractional_floats_rejected_400(self, lm):
        server = InferenceServer(lm, max_batch_size=4)
        try:
            with pytest.raises(InputValidationError) as e:
                server.predict([[1.5, 2.0, 3.0, 1.0, 1.0, 1.0, 1.0, 1.0]])
            assert e.value.status == 400
            with pytest.raises(InputValidationError):
                server.predict("definitely not features")
        finally:
            server.stop()

    def test_http_400_on_bad_dtype(self, lm):
        server = InferenceServer(lm, port=0, max_batch_size=4).start()
        try:
            req = urllib.request.Request(
                server.url + "/predict",
                data=json.dumps(
                    {"data": [[0.5, 1.0, 2.0, 3.0, 1.0, 1.0, 1.0,
                               1.0]]}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=10)
            assert e.value.code == 400
        finally:
            server.stop()

    def test_values_model_casts_float32(self):
        net = mlp_net()
        server = InferenceServer(net)
        try:
            out = server.predict([[0.25, 0.5, 0.75]])  # python lists
            assert out.dtype == np.float32
            assert out.shape == (1, 2)
        finally:
            server.stop()


# ------------------------------------------- cross-process zero compile


_CHILD_SCRIPT = r"""
import json, os
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu import observability as obs
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.serving import InferenceServer

obs.install_jax_compile_hook(obs.metrics)

def mlp(seed, n_in, n_hidden):
    return MultiLayerNetwork(
        (NeuralNetConfiguration.builder()
         .seed(seed).learning_rate(0.1).updater("sgd").weight_init("xavier")
         .list()
         .layer(DenseLayer(n_out=n_hidden, activation="relu"))
         .layer(OutputLayer(n_out=3, activation="softmax",
                            loss_function="mcxent"))
         .set_input_type(InputType.feed_forward(n_in))
         .build())).init()

server = InferenceServer(max_batch_size=8, max_delay_ms=1.0)  # 4 buckets
server.add_model("alpha", net=mlp(1, 4, 8))
server.add_model("beta", net=mlp(2, 6, 12))
for name in ("alpha", "beta"):
    server.models.get(name).batcher.warm()
if os.environ["CHILD_MODE"] == "traffic":
    # Mixed-shape traffic across both models: every request pads to a
    # pre-warmed bucket, so a warmed AOT store means zero compiles below.
    for name, n_in in (("alpha", 4), ("beta", 6)):
        for rows in (1, 2, 3, 5, 8):
            out = server.predict(np.zeros((rows, n_in), np.float32),
                                 model=name)
            assert out.shape == (rows, 3)
server.stop()

fam = obs.metrics.get_family("dl4j_xla_compiles_total")
total = sum(c.get() for c in fam.children()) if fam else 0.0
print(json.dumps({"xla_compiles": total,
                  "buckets": [1, 2, 4, 8]}))
"""


def _run_child(cache_dir, mode):
    env = dict(os.environ, JAX_PLATFORMS="cpu", CHILD_MODE=mode)
    env["DL4J_TPU_COMPILE_CACHE"] = cache_dir
    env.pop("XLA_FLAGS", None)  # plain 1-device CPU child
    proc = subprocess.run([sys.executable, "-c", _CHILD_SCRIPT],
                          capture_output=True, text=True, env=env,
                          timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


class TestZeroCompileServing:
    def test_two_models_four_buckets_zero_compiles_when_warm(self, tmp_path):
        cache = str(tmp_path / "serving-cache")
        cold = _run_child(cache, "warm")
        assert cold["xla_compiles"] > 0  # the warm child populates the store
        warm = _run_child(cache, "traffic")
        # The acceptance property: a fresh process serving mixed-shape
        # traffic for two models over a 4-bucket ladder never compiles —
        # every bucket replays from the AOT executable store.
        assert warm["xla_compiles"] == 0


# ------------------------------------------------------------- multi-model


class TestMultiModelHost:
    def _save(self, net, path):
        from deeplearning4j_tpu.checkpoint import CheckpointManager

        CheckpointManager(str(path), async_save=False).save(net)
        return str(path)

    def test_routing_and_v1_models(self, tmp_path):
        a, b = mlp_net(seed=1), mlp_net(seed=2)
        server = InferenceServer(port=0, max_delay_ms=1.0)
        server.add_model("a", path=self._save(a, tmp_path / "a"))
        server.add_model("b", path=self._save(b, tmp_path / "b"))
        server.start()
        try:
            x = np.random.RandomState(0).rand(3, 3).astype(np.float32)
            np.testing.assert_allclose(server.predict(x, model="a"),
                                       np.asarray(a.output(x)), rtol=1e-5)
            np.testing.assert_allclose(server.predict(x, model="b"),
                                       np.asarray(b.output(x)), rtol=1e-5)
            with urllib.request.urlopen(server.url + "/v1/models",
                                        timeout=10) as r:
                rows = {m["name"]: m for m in json.loads(r.read())["models"]}
            assert set(rows) == {"a", "b"}
            for row in rows.values():
                assert row["resident"] and row["hbm_bytes"] > 0
            # Unknown model routes to a 404, not a traceback 500.
            req = urllib.request.Request(
                server.url + "/predict",
                data=json.dumps({"data": x.tolist(),
                                 "model": "nope"}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=10)
            assert e.value.code == 404
        finally:
            server.stop()

    def test_lru_eviction_and_reload_under_budget(self, tmp_path):
        a, b = mlp_net(seed=1), mlp_net(seed=2)
        pa = self._save(a, tmp_path / "a")
        pb = self._save(b, tmp_path / "b")
        # A budget smaller than one model: at most one stays resident.
        server = InferenceServer(port=0, hbm_budget_bytes=1)
        server.add_model("a", path=pa)
        server.add_model("b", path=pb)
        try:
            snap = {m["name"]: m for m in server.models.snapshot()}
            assert snap["b"]["resident"] and not snap["a"]["resident"]
            ev0 = _counter_total("dl4j_serving_evictions_total")
            x = np.zeros((1, 3), np.float32)
            # Using "a" reloads it and LRU-evicts "b"...
            np.testing.assert_allclose(server.predict(x, model="a"),
                                       np.asarray(a.output(x)), rtol=1e-5)
            snap = {m["name"]: m for m in server.models.snapshot()}
            assert snap["a"]["resident"] and not snap["b"]["resident"]
            assert _counter_total("dl4j_serving_evictions_total") > ev0
            # ...and "b" still serves correct predictions after its reload.
            np.testing.assert_allclose(server.predict(x, model="b"),
                                       np.asarray(b.output(x)), rtol=1e-5)
        finally:
            server.stop()


# ----------------------------------------------------------------- metrics


class TestSLOMetricsScrape:
    def test_one_scrape_carries_per_model_slo_series(self, lm):
        server = InferenceServer(lm, port=0, max_batch_size=4,
                                 max_delay_ms=1.0).start()
        try:
            server.predict(np.array([[1, 2, 3, 4, 5, 6, 7, 8]], np.int32))
            server.generate([1, 2], 3, temperature=0.0)
            with urllib.request.urlopen(server.url + "/metrics",
                                        timeout=10) as r:
                scrape = r.read().decode()
        finally:
            server.stop()
        for needle in (
                # per-model/route SLO latency histograms (p50/p99 source)
                'dl4j_serving_request_seconds_bucket{model="default",'
                'route="predict"',
                'dl4j_serving_request_seconds_bucket{model="default",'
                'route="generate"',
                'dl4j_serving_ttft_seconds_bucket{model="default"',
                'dl4j_serving_itl_seconds_bucket{model="default"',
                'dl4j_serving_decode_step_seconds_bucket{model="default"',
                # outcome-labeled request counter
                'dl4j_requests_total{model="default",route="predict",'
                'outcome="ok"}',
                # queue-depth and HBM gauges
                'dl4j_serving_model_queue_depth{model="default",'
                'route="predict"}',
                'dl4j_serving_model_hbm_bytes{model="default"}',
                'dl4j_serving_generated_tokens_total{model="default"}',
                # legacy families survive unchanged
                "dl4j_request_latency_seconds_bucket",
                "dl4j_serving_batch_size_bucket",
        ):
            assert needle in scrape, f"missing {needle} in /metrics"

    def test_metrics_json_format(self, lm):
        server = InferenceServer(lm, port=0).start()
        try:
            with urllib.request.urlopen(
                    server.url + "/metrics?format=json", timeout=10) as r:
                assert r.headers.get_content_type() == "application/json"
                doc = json.loads(r.read())
            assert "dl4j_serving_model_hbm_bytes" in doc
        finally:
            server.stop()
