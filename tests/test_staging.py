"""DeviceStager (datasets/staging.py) tests: the overlapped input
pipeline must be behaviorally invisible — staged training bit-identical
to the synchronous path through MLN, graph, and superstep — while the
in-flight window respects the byte budget (backpressure) and failure
paths leak zero in-flight HBM (gauges return to baseline). Plus the
AsyncDataSetIterator satellites: consumer-side input-wait observation
and reset() stopping a live worker before the base resets."""

import time

import numpy as np
import pytest

import jax

from deeplearning4j_tpu import NeuralNetConfiguration
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets import staging
from deeplearning4j_tpu.datasets.staging import (
    _M_DEPTH,
    _M_INFLIGHT,
    DeviceStager,
    host_item_nbytes,
    maybe_stage,
    stage_item,
    staging_budget_bytes,
    staging_enabled,
)
from deeplearning4j_tpu.datasets.iterators import (
    AsyncDataSetIterator,
    ListDataSetIterator,
    SuperbatchIterator,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization,
    DenseLayer,
    DropoutLayer,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu import observability as _obs

from conftest import make_classification_data

N_IN, N_OUT = 4, 3


def mlp_conf(superstep_k=0):
    return (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.05).updater("adam").weight_init("xavier")
            .superstep_k(superstep_k)
            .list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(BatchNormalization())
            .layer(DropoutLayer(dropout=0.5))
            .layer(OutputLayer(n_out=N_OUT, activation="softmax",
                               loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(N_IN)).build())


def graph_conf(superstep_k=0):
    return (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.05).updater("adam").weight_init("xavier")
            .superstep_k(superstep_k)
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_out=8, activation="relu"), "in")
            .add_layer("out", OutputLayer(n_out=N_OUT, activation="softmax",
                                          loss_function="mcxent"), "d")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(N_IN))
            .build())


def make_batches(rng, n_batches=6, batch=6):
    out = []
    for _ in range(n_batches):
        X, Y = make_classification_data(rng, n=batch, n_features=N_IN,
                                        n_classes=N_OUT, dtype="float32")
        out.append(DataSet(X, Y))
    return out


def assert_trees_identical(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def gauges():
    return (_M_INFLIGHT.get(), _M_DEPTH.get())


# --------------------------------------------------------------- streaming


class TestDeviceStager:
    def test_same_data_same_order(self, rng):
        batches = make_batches(rng)
        staged = list(DeviceStager(batches))
        assert len(staged) == len(batches)
        for got, want in zip(staged, batches):
            assert not isinstance(got.features, np.ndarray)  # device-resident
            np.testing.assert_array_equal(np.asarray(got.features),
                                          want.features)
            np.testing.assert_array_equal(np.asarray(got.labels), want.labels)

    def test_host_only_mode_passes_items_through(self, rng):
        batches = make_batches(rng)
        staged = list(DeviceStager(batches, device_stage=False))
        assert [s is b for s, b in zip(staged, batches)] == [True] * len(batches)

    def test_gauges_return_to_baseline_after_epoch(self, rng):
        base = gauges()
        list(DeviceStager(make_batches(rng)))
        assert gauges() == pytest.approx(base)

    def test_maybe_stage_passthroughs(self, rng, monkeypatch):
        batches = make_batches(rng)
        # single-batch list: the fit(ds)/elastic path stays synchronous
        single = [batches[0]]
        assert maybe_stage(single) is single
        # already-staging sources are not double-wrapped
        async_it = AsyncDataSetIterator(batches)
        assert maybe_stage(async_it) is async_it
        sbit = SuperbatchIterator(batches, k=2)
        assert maybe_stage(sbit) is sbit
        # kill switch
        monkeypatch.setenv("DL4J_TPU_STAGING", "0")
        assert not staging_enabled()
        assert maybe_stage(batches) is batches
        monkeypatch.delenv("DL4J_TPU_STAGING")
        wrapped = maybe_stage(batches)
        assert isinstance(wrapped, DeviceStager)
        wrapped.close()

    def test_budget_env_override(self, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_STAGE_BYTES", "12345")
        assert staging_budget_bytes() == 12345


# ------------------------------------------------------------ backpressure


class TestBackpressure:
    def test_inflight_never_exceeds_budget(self, rng, monkeypatch):
        batches = make_batches(rng, n_batches=8, batch=16)
        per = host_item_nbytes(batches[0])
        budget = 2 * per + per // 2  # window fits two batches, not three
        monkeypatch.setenv("DL4J_TPU_STAGE_BYTES", str(budget))
        stager = DeviceStager(batches, depth=8)
        seen = 0
        for _ in stager:
            time.sleep(0.02)  # slow consumer: let the worker run ahead
            seen += 1
        assert seen == len(batches)
        assert stager.max_inflight_bytes > 0
        assert stager.max_inflight_bytes <= budget

    def test_oversized_item_admitted_alone(self, rng, monkeypatch):
        batches = make_batches(rng, n_batches=4, batch=16)
        per = host_item_nbytes(batches[0])
        monkeypatch.setenv("DL4J_TPU_STAGE_BYTES", str(per // 4))
        # Budget below one batch: the window shrinks to one-at-a-time
        # instead of erroring.
        stager = DeviceStager(batches, depth=8)
        assert len(list(stager)) == len(batches)
        assert stager.max_inflight_bytes == per


# ------------------------------------------------------------ failure paths


class TestFailurePaths:
    def test_producer_error_surfaces_with_zero_leaked_hbm(self, rng):
        batches = make_batches(rng, n_batches=3)
        base = gauges()

        def boom():
            yield batches[0]
            yield batches[1]
            raise RuntimeError("boom mid-stream")

        stager = DeviceStager(boom(), depth=2)
        with pytest.raises(RuntimeError, match="boom mid-stream"):
            for _ in stager:
                pass
        stager.close()
        assert gauges() == pytest.approx(base)

    def test_abandoned_iteration_drops_staged_buffers(self, rng):
        batches = make_batches(rng, n_batches=6)
        base = gauges()
        stager = DeviceStager(batches, depth=4)
        next(iter(stager))  # consume one, abandon the rest
        stager.close()
        assert gauges() == pytest.approx(base)
        # closed stagers iterate as exhausted
        assert list(stager) == []

    def test_engine_fit_propagates_producer_error(self, rng):
        net = MultiLayerNetwork(mlp_conf())
        net.init()
        batches = make_batches(rng, n_batches=3)

        def boom():
            yield batches[0]
            yield batches[1]
            raise RuntimeError("stream died")

        base = gauges()
        with pytest.raises(RuntimeError, match="stream died"):
            net.fit(boom())
        assert gauges() == pytest.approx(base)


# ----------------------------------------------- AsyncDataSetIterator fixes


class TestAsyncIteratorSatellites:
    def test_consumer_wait_observed_under_source_async(self, rng):
        child = _obs.metrics.histogram(
            "dl4j_input_wait_seconds", label_names=("source",)
        ).labels(source="async")
        _, _, _, c0 = child.histogram_state()
        list(AsyncDataSetIterator(make_batches(rng), queue_size=2))
        _, _, _, c1 = child.histogram_state()
        assert c1 > c0

    def test_staging_wait_records_producer_stalls(self, rng):
        fam = _obs.metrics.get_family("dl4j_staging_wait_seconds")
        assert fam is not None
        (child,) = list(fam.children())
        _, _, _, c0 = child.histogram_state()
        list(AsyncDataSetIterator(make_batches(rng), queue_size=2))
        _, _, _, c1 = child.histogram_state()
        assert c1 > c0

    def test_reset_stops_live_worker_before_base_reset(self, rng):
        batches = make_batches(rng, n_batches=6)
        base_it = ListDataSetIterator(batches, batch_size=6)
        async_it = AsyncDataSetIterator(base_it, queue_size=2)
        gauge0 = gauges()
        it = iter(async_it)
        next(it)  # worker is live, part-way through the base
        async_it.reset()  # must stop + drain the worker, then reset base
        assert async_it._active is None
        assert gauges() == pytest.approx(gauge0)
        # A fresh epoch sees the FULL stream, in order, from the start.
        replay = list(async_it)
        assert len(replay) == len(batches)
        for got, want in zip(replay, batches):
            np.testing.assert_array_equal(np.asarray(got.features),
                                          want.features)

    def test_reiter_closes_prior_worker(self, rng):
        async_it = AsyncDataSetIterator(make_batches(rng), queue_size=2)
        first = iter(async_it)
        next(first)
        second = iter(async_it)  # prior epoch's stager must be closed
        assert first._closed
        assert len(list(second)) == 6


# -------------------------------------------------------------- bit-identity


class TestBitIdentity:
    def _fit_both(self, make_net, batches, monkeypatch, epochs=2):
        monkeypatch.setenv("DL4J_TPU_STAGING", "0")
        sync_net = make_net()
        for _ in range(epochs):
            sync_net.fit(batches)
        monkeypatch.delenv("DL4J_TPU_STAGING")
        assert staging_enabled()
        staged_net = make_net()
        for _ in range(epochs):
            staged_net.fit(batches)
        return sync_net, staged_net

    def test_mln_staged_matches_synchronous(self, rng, monkeypatch):
        batches = make_batches(rng)
        a, b = self._fit_both(lambda: MultiLayerNetwork(mlp_conf()),
                              batches, monkeypatch)
        assert_trees_identical(a.params_tree, b.params_tree)
        assert_trees_identical(a.opt_state, b.opt_state)

    def test_graph_staged_matches_synchronous(self, rng, monkeypatch):
        batches = make_batches(rng)
        a, b = self._fit_both(lambda: ComputationGraph(graph_conf()),
                              batches, monkeypatch)
        assert_trees_identical(a.params_tree, b.params_tree)
        assert_trees_identical(a.opt_state, b.opt_state)

    def test_superstep_staged_matches_synchronous(self, rng, monkeypatch):
        batches = make_batches(rng, n_batches=10)  # k=4: two blocks + tail 2
        a, b = self._fit_both(lambda: MultiLayerNetwork(mlp_conf(superstep_k=4)),
                              batches, monkeypatch)
        assert_trees_identical(a.params_tree, b.params_tree)
        assert_trees_identical(a.opt_state, b.opt_state)

    def test_stage_item_handles_superbatch_containers(self, rng):
        from deeplearning4j_tpu.datasets.iterators import stack_superbatch

        batches = make_batches(rng, n_batches=3)
        sb = stack_superbatch(batches, stage=False)
        staged = stage_item(sb)
        assert type(staged).__name__ == "Superbatch"
        assert staged.k == 3
        assert not isinstance(staged.features, np.ndarray)
        np.testing.assert_array_equal(
            np.asarray(staged.features),
            np.stack([b.features for b in batches]))
