"""Persistent compile cache + AOT warmup (`deeplearning4j_tpu/compilation/`).

Covers the acceptance criteria of the compile-cache PR: fingerprint
invalidation (config / static-args / mesh / version changes each force a
miss), corrupt-artifact fallback (warning + bit-identical results),
warmup-then-fit with ZERO first-batch traces in a fresh process (checked
via `dl4j_xla_compiles_total` in a subprocess), the CLI, and the serving
readiness protocol (`/healthz`, 503 while warming).
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import (MultiLayerNetwork, NeuralNetConfiguration,
                                compilation)
from deeplearning4j_tpu import observability as obs
from deeplearning4j_tpu.compilation import store as store_mod
from deeplearning4j_tpu.compilation import warmup as warmup_mod
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer


def mlp_conf(n_in=4, n_out=3, seed=42, lr=0.1):
    return (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(lr).updater("sgd")
            .weight_init("xavier")
            .list()
            .layer(DenseLayer(n_out=8, activation="relu"))
            .layer(OutputLayer(n_out=n_out, activation="softmax",
                               loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(n_in))
            .build())


def small_dataset(n=16, n_in=4, n_out=3, seed=0):
    r = np.random.RandomState(seed)
    x = r.rand(n, n_in).astype("float32")
    y = np.eye(n_out, dtype="float32")[r.randint(0, n_out, n)]
    return DataSet(x, y)


@pytest.fixture
def cache_dir(tmp_path, monkeypatch):
    """Fresh per-test cache root (the session default from conftest stays
    untouched); resets the store singleton on both sides."""
    d = str(tmp_path / "compile-cache")
    monkeypatch.setenv(compilation.ENV_KNOB, d)
    compilation.reset()
    yield d
    compilation.reset()


def _counter_total(name, source=None):
    fam = obs.metrics.get_family(name)
    if fam is None:
        return 0.0
    total = 0.0
    for child in fam.children():
        if source is not None and child.labels.get("source") != source:
            continue
        total += child.get()
    return total


# ------------------------------------------------------------ fingerprint


class TestFingerprint:
    def _doc(self, net=None, static=None, ds=None):
        net = net or MultiLayerNetwork(mlp_conf())
        if not net._initialized:
            net.init()
        ds = ds or small_dataset()
        args = warmup_mod._mln_args(net, ds, "train_step")
        return store_mod.build_fingerprint_doc(net, "train_step",
                                               static or {}, args)

    def test_stable_for_identical_inputs(self):
        net = MultiLayerNetwork(mlp_conf())
        net.init()
        ds = small_dataset()
        fp1 = store_mod.fingerprint(self._doc(net, ds=ds))
        fp2 = store_mod.fingerprint(self._doc(net, ds=ds))
        assert fp1 == fp2

    def test_model_config_edit_forces_miss(self):
        base = store_mod.fingerprint(self._doc())
        edited = MultiLayerNetwork(mlp_conf(lr=0.2))
        edited.init()
        assert store_mod.fingerprint(self._doc(edited)) != base

    def test_superstep_k_change_forces_miss(self):
        net = MultiLayerNetwork(mlp_conf())
        net.init()
        ds = small_dataset()
        fp2 = store_mod.fingerprint(self._doc(net, {"k": 2}, ds))
        fp4 = store_mod.fingerprint(self._doc(net, {"k": 4}, ds))
        assert fp2 != fp4

    def test_mesh_context_forces_miss(self):
        import jax

        from deeplearning4j_tpu.parallel import mesh as mesh_mod
        from deeplearning4j_tpu.parallel.context import (ParallelContext,
                                                         parallel_context)

        net = MultiLayerNetwork(mlp_conf())
        net.init()
        ds = small_dataset()
        base = store_mod.fingerprint(self._doc(net, ds=ds))
        mesh = mesh_mod.create_mesh(devices=jax.devices()[:2])
        ctx = ParallelContext(mesh=mesh, data_axis=mesh.axis_names[0])
        with parallel_context(ctx):
            sharded = store_mod.fingerprint(self._doc(net, ds=ds))
        assert sharded != base

    def test_version_bump_forces_miss(self):
        doc = self._doc()
        bumped = dict(doc, jax="999.0.0")
        assert store_mod.fingerprint(bumped) != store_mod.fingerprint(doc)

    def test_batch_signature_forces_miss(self):
        net = MultiLayerNetwork(mlp_conf())
        net.init()
        fp16 = store_mod.fingerprint(self._doc(net, ds=small_dataset(16)))
        fp32 = store_mod.fingerprint(self._doc(net, ds=small_dataset(32)))
        assert fp16 != fp32


# ------------------------------------------------------- store + fallback


class TestAOTStoreFallback:
    def test_warmup_writes_artifacts(self, cache_dir):
        net = MultiLayerNetwork(mlp_conf())
        net.init()
        summary = net.warmup(small_dataset())
        assert summary["programs"] >= 3
        assert summary["compiled"] + summary["aot"] >= 3
        aot = os.path.join(cache_dir, "aot")
        assert any(f.endswith(".jaxec") for f in os.listdir(aot))

    def test_corrupt_artifact_warns_and_falls_back(self, cache_dir):
        ds = small_dataset()
        net = MultiLayerNetwork(mlp_conf())
        net.init()
        net.warmup(ds, kinds=["output"])
        aot = os.path.join(cache_dir, "aot")
        for name in os.listdir(aot):
            if name.endswith(".jaxec"):
                with open(os.path.join(aot, name), "wb") as f:
                    f.write(b"\x00corrupt garbage\xff")
        compilation.reset()  # fresh store: drop the in-process executables

        fresh = MultiLayerNetwork(mlp_conf())
        fresh.init()
        with pytest.warns(UserWarning, match="unusable AOT"):
            out = np.asarray(fresh.output(ds.features))

        clean = MultiLayerNetwork(mlp_conf())
        clean.init()
        expected = np.asarray(clean.output(ds.features))
        np.testing.assert_array_equal(out, expected)

    def test_disabled_knob_returns_raw_program(self, monkeypatch):
        monkeypatch.setenv(compilation.ENV_KNOB, "0")
        compilation.reset()
        try:
            assert compilation.cache_root() is None
            assert compilation.get_store() is None
            sentinel = object()
            assert compilation.wrap_program(sentinel, None, "output",
                                            {}) is sentinel
        finally:
            monkeypatch.undo()
            compilation.reset()


# ---------------------------------------------------------------- warmup


class TestWarmup:
    def test_warmup_then_fit_compiles_nothing_new(self, cache_dir):
        obs.install_jax_compile_hook(obs.metrics)
        ds = small_dataset()
        net = MultiLayerNetwork(mlp_conf())
        net.init()
        params_before = [np.asarray(p) for p in
                         __import__("jax").tree_util.tree_leaves(
                             net.params_tree)]
        net.warmup(ds)
        params_after = [np.asarray(p) for p in
                        __import__("jax").tree_util.tree_leaves(
                            net.params_tree)]
        for a, b in zip(params_before, params_after):
            np.testing.assert_array_equal(a, b)

        compiles_before = _counter_total("dl4j_xla_compiles_total")
        net.fit(ds)
        net.output(ds.features)
        assert _counter_total("dl4j_xla_compiles_total") == compiles_before

    def test_background_warmup_thread(self, cache_dir):
        net = MultiLayerNetwork(mlp_conf())
        net.init()
        thread = net.warmup(small_dataset(), background=True)
        thread.join(timeout=120)
        assert not thread.is_alive()
        assert thread.warmup_error is None
        assert thread.warmup_result["programs"] >= 3

    def test_synthetic_dataset_from_input_type(self):
        net = MultiLayerNetwork(mlp_conf())
        ds = warmup_mod.synthetic_dataset(net, 8)
        assert np.asarray(ds.features).shape == (8, 4)
        assert np.asarray(ds.labels).shape == (8, 3)


_CHILD_SCRIPT = r"""
import json, os
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu import observability as obs
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer

obs.install_jax_compile_hook(obs.metrics)
conf = (NeuralNetConfiguration.builder()
        .seed(42).learning_rate(0.1).updater("sgd").weight_init("xavier")
        .list()
        .layer(DenseLayer(n_out=8, activation="relu"))
        .layer(OutputLayer(n_out=3, activation="softmax",
                           loss_function="mcxent"))
        .set_input_type(InputType.feed_forward(4))
        .build())
net = MultiLayerNetwork(conf)
net.init()
r = np.random.RandomState(0)
x = r.rand(16, 4).astype("float32")
y = np.eye(3, dtype="float32")[r.randint(0, 3, 16)]
ds = DataSet(x, y)
mode = os.environ["CHILD_MODE"]
if mode == "warm":
    net.warmup(ds)
else:
    net.fit(ds)
    net.output(x)

def total(name, source=None):
    fam = obs.metrics.get_family(name)
    if fam is None:
        return 0.0
    return sum(c.get() for c in fam.children()
               if source is None or c.labels.get("source") == source)

print(json.dumps({
    "xla_compiles": total("dl4j_xla_compiles_total"),
    "aot_hits": total("dl4j_compile_cache_hits_total", "aot"),
    "aot_misses": total("dl4j_compile_cache_misses_total", "aot"),
}))
"""


def _run_child(cache_dir, mode):
    env = dict(os.environ, JAX_PLATFORMS="cpu", CHILD_MODE=mode)
    env["DL4J_TPU_COMPILE_CACHE"] = cache_dir
    env.pop("XLA_FLAGS", None)  # plain 1-device CPU child
    proc = subprocess.run([sys.executable, "-c", _CHILD_SCRIPT],
                          capture_output=True, text=True, env=env,
                          timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


class TestCrossProcessWarmStart:
    def test_populated_cache_means_zero_traces_in_fresh_process(
            self, tmp_path):
        cache = str(tmp_path / "shared-cache")
        cold = _run_child(cache, "warm")
        assert cold["xla_compiles"] > 0
        assert cold["aot_misses"] > 0
        warm = _run_child(cache, "fit")
        # The whole point of the PR: a fresh process replays every seen
        # program from the executable store — zero full XLA traces.
        assert warm["xla_compiles"] == 0
        assert warm["aot_hits"] >= 2  # train_step + output at minimum


class TestWarmupCLI:
    def test_cli_smoke(self, tmp_path):
        from deeplearning4j_tpu.checkpoint import save_checkpoint

        net = MultiLayerNetwork(mlp_conf())
        net.init()
        ckpt = str(tmp_path / "ckpt")
        save_checkpoint(net, ckpt)
        cache = str(tmp_path / "cli-cache")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)
        env.pop("DL4J_TPU_COMPILE_CACHE", None)
        proc = subprocess.run(
            [sys.executable, "-m", "deeplearning4j_tpu.compilation.warmup",
             ckpt, "--batch-size", "4", "--cache-dir", cache],
            capture_output=True, text=True, env=env, timeout=300)
        assert proc.returncode == 0, proc.stderr[-2000:]
        summary = json.loads(proc.stdout.strip().splitlines()[-1])
        assert summary["programs"] >= 1
        assert summary["cache_dir"] == cache
        assert os.path.isdir(os.path.join(cache, "aot"))
        assert any(f.endswith(".jaxec")
                   for f in os.listdir(os.path.join(cache, "aot")))


# --------------------------------------------------------------- serving


class _BlockingNet:
    """output() blocks until released — holds the server in "warming"."""

    def __init__(self):
        self.release = threading.Event()
        self.calls = 0

    def output(self, x):
        self.calls += 1
        if self.calls == 1:  # only the warmup batch blocks
            self.release.wait(timeout=60)
        return np.zeros((np.asarray(x).shape[0], 2), np.float32)


def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return json.loads(resp.read())


class TestServingWarmup:
    def test_healthz_and_503_while_warming(self):
        from deeplearning4j_tpu.serving import InferenceServer

        net = _BlockingNet()
        server = InferenceServer(net, max_batch_size=4, warmup=True,
                                 warmup_shape=(3,),
                                 predict_timeout_s=30.0).start()
        try:
            deadline = time.monotonic() + 10
            while (server._status != "warming"
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert _get_json(server.url + "/healthz")["status"] == "warming"

            req = urllib.request.Request(
                server.url + "/predict",
                data=json.dumps({"data": [[0.0, 0.0, 0.0]]}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                urllib.request.urlopen(req, timeout=10)
            assert exc_info.value.code == 503
            assert exc_info.value.headers.get("Retry-After") == "1"

            net.release.set()
            assert server.wait_ready(timeout=30)
            assert _get_json(server.url + "/healthz")["status"] == "ready"
            with urllib.request.urlopen(req, timeout=30) as resp:
                preds = json.loads(resp.read())["predictions"]
            assert len(preds) == 1
        finally:
            net.release.set()
            server.stop()

    def test_warmed_first_request_latency_near_steady_state(self, cache_dir):
        from deeplearning4j_tpu.serving import InferenceServer

        net = MultiLayerNetwork(mlp_conf())
        net.init()
        server = InferenceServer(net, max_batch_size=8, max_delay_ms=1.0,
                                 warmup=True).start()
        try:
            assert server.wait_ready(timeout=120)
            fam = obs.metrics.get_family("dl4j_request_latency_seconds")
            count0 = fam.summarize().get("count", 0)
            row = [[0.1, 0.2, 0.3, 0.4]]
            times = []
            for _ in range(6):
                t0 = time.perf_counter()
                server.predict(row)
                times.append(time.perf_counter() - t0)
            assert fam.summarize()["count"] == count0 + 6
            steady = sorted(times[1:])[len(times[1:]) // 2]
            # Warmed: the first request pays no XLA compile, so it sits
            # within 2x of steady state (floor absorbs scheduler noise on
            # sub-millisecond CPU batches).
            assert times[0] <= max(2.0 * steady, 0.25)
        finally:
            server.stop()


# ------------------------------------------- fused-bottleneck serving warmup


class TestBottleneckServingWarmup:
    """PR 19 regression: `warmup_buckets` (the serving batcher's warm path)
    must warm the fused `BottleneckBlock` layer's resolved kernel signature
    for resnet-family checkpoints — an int8-quantized fused checkpoint then
    serves over HTTP with ZERO XLA compiles across the bucket ladder."""

    def _fused_conf(self):
        from deeplearning4j_tpu.models.resnet import (_bottleneck_fused,
                                                      _conv_bn)
        from deeplearning4j_tpu.nn.conf.layers import GlobalPoolingLayer

        b = (NeuralNetConfiguration.builder()
             .seed(9).learning_rate(0.01).updater("nesterovs").momentum(0.9)
             .weight_init("relu").dtype("float32")
             .graph_builder().add_inputs("input"))
        x = _conv_bn(b, "stem", "input", 8, (1, 1), (1, 1))
        x = _bottleneck_fused(b, "b0", x, 2, (1, 1), project=False)
        x = _bottleneck_fused(b, "b1", x, 2, (2, 2), project=True)
        b.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), x)
        b.add_layer("fc", OutputLayer(n_out=3, activation="softmax",
                                      loss_function="mcxent",
                                      weight_init="xavier"), "avgpool")
        return (b.set_outputs("fc")
                .set_input_types(InputType.convolutional(6, 6, 3))
                .build())

    def test_int8_checkpoint_serves_zero_compiles_after_warmup(
            self, cache_dir, tmp_path):
        from deeplearning4j_tpu.checkpoint import load_any, save_checkpoint
        from deeplearning4j_tpu.checkpoint.quantize import quantize_checkpoint
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.serving import InferenceServer

        rng = np.random.RandomState(5)
        X = rng.randn(4, 6, 6, 3).astype(np.float32)
        Y = np.eye(3, dtype=np.float32)[rng.randint(0, 3, 4)]
        net = ComputationGraph(self._fused_conf()).init()
        net.fit(DataSet(X, Y))
        src = str(tmp_path / "step1")
        dst = str(tmp_path / "step1-int8")
        save_checkpoint(net, src)
        quantize_checkpoint(src, dst)
        srv = load_any(dst)
        blk = srv.params_tree["b0_block"]
        assert blk["W_a"].dtype == np.int8 and "W_a__scale" in blk

        obs.install_jax_compile_hook(obs.metrics)
        server = InferenceServer(srv, max_batch_size=4, max_delay_ms=1.0,
                                 warmup=True).start()
        try:
            assert server.wait_ready(timeout=300)
            # Reference outputs first: the direct output() below runs at
            # exact (unpadded) row counts, which are NOT all bucket shapes.
            refs = {rows: np.asarray(srv.output(X[:rows]))[0]
                    for rows in (1, 2, 3, 4)}
            compiles_before = _counter_total("dl4j_xla_compiles_total")
            for rows in (1, 2, 3, 4):  # every bucket of the ladder
                req = urllib.request.Request(
                    server.url + "/predict",
                    data=json.dumps({"data": X[:rows].tolist()}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=60) as resp:
                    preds = np.asarray(json.loads(resp.read())["predictions"])
                assert preds.shape == (rows, 3)
                np.testing.assert_allclose(preds, refs[rows], rtol=1e-4,
                                           atol=1e-5)
            assert (_counter_total("dl4j_xla_compiles_total")
                    == compiles_before)
        finally:
            server.stop()
