"""Elastic fault-tolerant training tests: retry/backoff, the fault
harness, the host coordinator, checkpoint fallback, and ElasticTrainer
end to end — including REAL multi-process chaos runs (kill a worker,
hang the coordinator, truncate the newest checkpoint) that must recover
onto the survivor and land float-close to an unfaulted run.

The multi-process tests use the host-side coordinator transport
(`parallel/coordinator.py`), which works on CPU CI where cross-process
XLA collectives don't — that is the elastic path's whole point.
Equivalence maths: per-step parameter averaging after identical-start
SGD updates equals gradient averaging, and the mean gradient over two
equal half-batches equals the full-batch gradient — so a 2-worker
averaged run (and a recovered 1-worker run on full batches) must both
match plain single-machine training on the full batch stream.
"""

import json
import os
import socket
import subprocess
import sys
import textwrap
import threading

import numpy as np
import pytest

from deeplearning4j_tpu.checkpoint.array_store import CheckpointCorruptError
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (
    ListDataSetIterator, fast_forward)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.observability.elastic import EVENTS
from deeplearning4j_tpu.parallel.coordinator import (
    ClusterChanged, Coordinator, CoordinatorClient, CoordinatorError,
    parse_address)
from deeplearning4j_tpu.parallel.elastic import ElasticTrainer
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
from deeplearning4j_tpu.util.faultinject import (
    FaultPlan, truncate_newest_chunk)
from deeplearning4j_tpu.util.retry import Backoff, RetryError, with_retries

# --------------------------------------------------------------- helpers

CONF_CODE = textwrap.dedent("""
    import numpy as np
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration

    def make_conf():
        return (NeuralNetConfiguration.builder()
                .seed(7).learning_rate(0.1).updater("sgd")
                .list()
                .layer(DenseLayer(n_out=16, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss_function="mcxent"))
                .set_input_type(InputType.feed_forward(4))
                .build())

    def make_data(step):
        r = np.random.RandomState(100 + step)
        X = r.randn(16, 4).astype("float32")
        Y = np.eye(3)[r.randint(0, 3, 16)].astype("float32")
        return X, Y
""")

_NS = {}
exec(CONF_CODE, _NS)
make_conf, make_data = _NS["make_conf"], _NS["make_data"]


def full_batch(step):
    X, Y = make_data(step)
    return DataSet(X, Y)


def shard_fn(step, rank, world):
    """Each worker's slice of the step's 16-row batch; the concatenation
    across ranks is exactly the full batch (the equivalence contract)."""
    X, Y = make_data(step)
    n = X.shape[0] // world
    return DataSet(X[rank * n:(rank + 1) * n], Y[rank * n:(rank + 1) * n])


def reference_params(steps):
    """Plain single-machine training on the full batch stream."""
    net = MultiLayerNetwork(make_conf()).init()
    w = ParallelWrapper(net, workers=1)
    for s in range(steps):
        w.fit(full_batch(s))
    return net


def flat_params(net):
    return {f"{lk}/{pk}": np.asarray(v)
            for lk, layer in net.params_tree.items()
            for pk, v in layer.items()}


def assert_params_close(got, net, rtol=1e-4, atol=1e-6):
    want = flat_params(net)
    assert set(got) == set(want)
    for k in want:
        np.testing.assert_allclose(np.asarray(got[k]), want[k],
                                   rtol=rtol, atol=atol, err_msg=f"param {k}")


def event_count(event):
    return EVENTS.labels(event=event).get()


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ------------------------------------------------------------ util/retry

def test_backoff_schedule_and_budget():
    sleeps = []
    bo = Backoff(base_s=0.1, max_s=0.4, tries=4, jitter=False,
                 _sleep=sleeps.append)
    calls = []

    def always_fails():
        calls.append(1)
        raise OSError("nope")

    with pytest.raises(RetryError) as ei:
        bo.run(always_fails, retry_on=(OSError,), describe="doomed")
    assert len(calls) == 4            # tries counts attempts
    assert sleeps == [0.1, 0.2, 0.4]  # exponential, capped, no jitter
    assert isinstance(ei.value.last, OSError)

    # Full jitter: sleep is uniform in [0, cap] — pinned rand halves it.
    bo2 = Backoff(base_s=0.1, max_s=10.0, tries=3, _sleep=sleeps.append,
                  _rand=lambda: 0.5)
    assert bo2.sleep_for(0) == pytest.approx(0.05)
    assert bo2.sleep_for(3) == pytest.approx(0.4)

    # Succeeds mid-way: returns the value, stops retrying.
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert bo.run(flaky, retry_on=(OSError,)) == "ok"
    assert state["n"] == 3

    # Non-retryable exception escapes untouched.
    with pytest.raises(ValueError):
        bo.run(lambda: (_ for _ in ()).throw(ValueError("bad")),
               retry_on=(OSError,))


def test_with_retries_env_knobs(monkeypatch):
    monkeypatch.setenv("DL4J_TPU_RETRY_TRIES", "2")
    monkeypatch.setenv("DL4J_TPU_RETRY_BASE_S", "0.0")
    calls = []

    def fails():
        calls.append(1)
        raise OSError("x")

    with pytest.raises(RetryError):
        with_retries(fails, retry_on=(OSError,))
    assert len(calls) == 2  # env default picked up
    calls.clear()
    with pytest.raises(RetryError):
        with_retries(fails, tries=3, retry_on=(OSError,))
    assert len(calls) == 3  # explicit kwarg wins


# ------------------------------------------------------- util/faultinject

def test_fault_plan_parsing(tmp_path, monkeypatch):
    plan = FaultPlan.from_json(
        '[{"kind": "kill", "step": 7, "worker": 1},'
        ' {"kind": "hang_coordinator", "step": 1, "seconds": 2.5}]')
    assert len(plan.faults) == 2 and bool(plan)
    assert plan.faults[0].worker == 1
    assert plan.faults[1].worker is None
    assert plan.faults[1].args == {"seconds": 2.5}

    with pytest.raises(ValueError):
        FaultPlan.from_json('{"kind": "kill", "step": 1}')  # not a list
    with pytest.raises(ValueError):
        FaultPlan.from_json('[{"kind": "meteor", "step": 1}]')
    with pytest.raises(ValueError):
        FaultPlan.from_json('[{"kind": "kill"}]')  # no step

    monkeypatch.delenv("DL4J_TPU_FAULT_PLAN", raising=False)
    assert not FaultPlan.from_env()
    monkeypatch.setenv("DL4J_TPU_FAULT_PLAN",
                       '[{"kind": "preempt", "step": 3}]')
    assert FaultPlan.from_env().faults[0].kind == "preempt"
    plan_file = tmp_path / "plan.json"
    plan_file.write_text('[{"kind": "delay_h2d", "step": 2, "ms": 1}]')
    monkeypatch.setenv("DL4J_TPU_FAULT_PLAN", f"@{plan_file}")
    assert FaultPlan.from_env().faults[0].kind == "delay_h2d"


def test_fault_fire_once_and_filters():
    plan = FaultPlan.from_json(
        '[{"kind": "kill", "step": 5, "worker": 1},'
        ' {"kind": "preempt", "step": 5},'
        ' {"kind": "hang_coordinator", "step": 6}]')
    hits = []
    handlers = {"kill": lambda f: hits.append("kill"),
                "preempt": lambda f: hits.append("preempt")}

    assert plan.maybe_fire(4, 1, handlers) == []        # wrong step
    fired = plan.maybe_fire(5, 0, handlers)             # rank filter
    assert [f.kind for f in fired] == ["preempt"]       # kill wants rank 1
    fired = plan.maybe_fire(5, 1, handlers)
    assert [f.kind for f in fired] == ["kill"]          # preempt fired once
    assert plan.maybe_fire(5, 1, handlers) == []        # fire-once
    # Handler-less hang is marked fired (no action) and reported.
    fired = plan.maybe_fire(6, 0, {})
    assert [f.kind for f in fired] == ["hang_coordinator"]
    assert hits == ["preempt", "kill"]


def test_truncate_newest_chunk(tmp_path):
    d = tmp_path / "step_00000004"
    d.mkdir()
    (d / "manifest.json").write_text("x" * 500)
    (d / "COMMIT").write_text("ok")
    (d / "chunk_small.bin").write_bytes(b"a" * 100)
    (d / "chunk_big.bin").write_bytes(b"b" * 1000)
    hit = truncate_newest_chunk(str(d), drop_bytes=64)
    assert hit.endswith("chunk_big.bin")  # largest non-manifest/COMMIT file
    assert os.path.getsize(d / "chunk_big.bin") == 936
    assert os.path.getsize(d / "manifest.json") == 500
    empty = tmp_path / "empty"
    empty.mkdir()
    assert truncate_newest_chunk(str(empty)) is None


# --------------------------------------------------- checkpoint fallback

def _managed_net(tmp_path, steps=2):
    net = MultiLayerNetwork(make_conf()).init()
    wrapper = ParallelWrapper(net, workers=1)
    mgr = wrapper.checkpoint_manager(str(tmp_path / "ckpt"),
                                     async_save=False)
    for s in range(steps):
        wrapper.fit(full_batch(s))
        mgr.save(net)
    return net, wrapper, mgr


def test_manager_maybe_save_cadence(tmp_path):
    net = MultiLayerNetwork(make_conf()).init()
    wrapper = ParallelWrapper(net, workers=1)
    mgr = wrapper.checkpoint_manager(str(tmp_path / "c"), async_save=False,
                                     save_every=3)
    assert mgr.maybe_save(net, step=0) is None   # step 0 never saves
    assert mgr.maybe_save(net, step=2) is None
    assert mgr.maybe_save(net, step=3) is not None
    assert mgr.maybe_save(net, step=4) is None
    assert mgr.maybe_save(net, step=6) is not None
    assert mgr.all_steps() == [3, 6]
    off = wrapper.checkpoint_manager(str(tmp_path / "c2"), async_save=False)
    assert off.maybe_save(net, step=3) is None   # cadence disabled


def test_manager_restore_falls_back_past_corrupt_newest(tmp_path):
    net, wrapper, mgr = _managed_net(tmp_path)
    steps = mgr.all_steps()
    assert len(steps) == 2
    truncate_newest_chunk(mgr.step_path(steps[-1]))
    before = event_count("restore_fallback")
    fresh = MultiLayerNetwork(make_conf()).init()
    with pytest.warns(RuntimeWarning, match="corruption"):
        restored = mgr.restore(net=fresh)
    assert restored.iteration == steps[0]  # fell back to previous commit
    assert event_count("restore_fallback") >= before + 1
    # An explicitly named bad step still raises — the caller asked for it.
    with pytest.raises(CheckpointCorruptError):
        mgr.restore(step=steps[-1], net=fresh)
    # Every copy corrupt -> clean terminal error.
    truncate_newest_chunk(mgr.step_path(steps[0]))
    with pytest.raises(CheckpointCorruptError):
        with pytest.warns(RuntimeWarning):
            mgr.restore(net=fresh)


def test_fast_forward_iterator():
    batches = [full_batch(s) for s in range(5)]
    it = ListDataSetIterator(batches, batch_size=16)
    stream = fast_forward(it, 2)
    nxt = next(stream)
    np.testing.assert_array_equal(nxt.features, batches[2].features)
    assert len(list(stream)) == 2  # 3 and 4 remain
    # Past the end -> exhausted, not an error.
    assert list(fast_forward(it, 99)) == []


# ------------------------------------------------------- host coordinator

def test_coordinator_join_allreduce_and_barrier():
    coord = Coordinator(lost_after_s=30.0).start()
    try:
        results = {}

        def worker(wid, vec):
            c = CoordinatorClient(coord.address, wid, rpc_timeout_s=5.0)
            doc = c.join(expected=2, grace_s=10.0)
            c.barrier("start", step=0, timeout_s=10.0)
            mean = c.allreduce_mean("params", 1, {"v": np.asarray(vec)},
                                    timeout_s=10.0)
            # Idempotent re-ask: cached result, same mean, no double-count.
            again = c.allreduce_mean("params", 1, {"v": np.asarray(vec)},
                                     timeout_s=10.0)
            # Second barrier: leave() bumps the generation, which would
            # turn a peer's still-in-flight re-ask into ClusterChanged.
            c.barrier("done", step=1, timeout_s=10.0)
            results[wid] = (doc, mean, again)
            c.leave()

        ts = [threading.Thread(target=worker,
                               args=(wid, vec), daemon=True)
              for wid, vec in (("a", [1.0, 2.0]), ("b", [3.0, 4.0]))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
            assert not t.is_alive()
        assert results["a"][0]["rank"] == 0 and results["b"][0]["rank"] == 1
        assert results["a"][0]["world"] == 2
        for wid in ("a", "b"):
            np.testing.assert_allclose(results[wid][1]["v"], [2.0, 3.0])
            np.testing.assert_allclose(results[wid][2]["v"], [2.0, 3.0])
    finally:
        coord.close()


def test_coordinator_hang_survived_by_retry():
    coord = Coordinator(lost_after_s=30.0).start()
    try:
        c = CoordinatorClient(coord.address, "w", rpc_timeout_s=0.2)
        c.join(expected=1, grace_s=5.0)
        before = event_count("coordinator_retry")
        coord.inject_hang(0.8)
        doc = c.heartbeat()  # stalls > rpc timeout -> backoff retries
        assert doc["ok"] and doc["known"]
        assert event_count("coordinator_retry") >= before + 1
    finally:
        coord.close()


def test_coordinator_evicts_lost_host_and_unblocks_collective():
    coord = Coordinator(lost_after_s=0.6).start()
    try:
        a = CoordinatorClient(coord.address, "a", rpc_timeout_s=5.0)
        b = CoordinatorClient(coord.address, "b", rpc_timeout_s=5.0)
        docs = {}
        t = threading.Thread(
            target=lambda: docs.update(b=b.join(expected=2, grace_s=10.0)),
            daemon=True)
        t.start()
        a.join(expected=2, grace_s=10.0)
        t.join(timeout=10)
        a.start_heartbeats(0.15)
        before = event_count("host_lost")
        try:
            # "b" never heartbeats: the reaper evicts it mid-collective and
            # the survivor unblocks with ClusterChanged, not a hang.
            with pytest.raises(ClusterChanged):
                a.allreduce_mean("p", 1, {"v": np.ones(2)}, timeout_s=10.0)
            assert event_count("host_lost") >= before + 1
            # The heartbeat thread saw the new generation too.
            deadline = 20
            while not a.cluster_changed and deadline:
                threading.Event().wait(0.1)
                deadline -= 1
            with pytest.raises(ClusterChanged):
                a.check()
            # Re-join clears the flag and re-forms on the survivor.
            doc = a.join(expected=None, grace_s=1.0)
            assert doc["world"] == 1 and doc["members"] == ["a"]
            a.check()
        finally:
            a.stop_heartbeats()
    finally:
        coord.close()


def test_join_wait_survives_lease_shorter_than_grace():
    """A joiner blocked waiting for the expected world heartbeats only
    AFTER join returns — the coordinator must keep its lease fresh while
    it waits, or the reaper evicts the very worker sitting in the join
    (join grace > lease is the default configuration)."""
    coord = Coordinator(lost_after_s=0.3).start()
    try:
        c = CoordinatorClient(coord.address, "slowpoke", rpc_timeout_s=10.0)
        # The second worker never shows: the join blocks through several
        # full lease periods, then forms the cluster on whoever is there.
        doc = c.join(expected=2, grace_s=1.2)
        assert doc["ok"] and doc["members"] == ["slowpoke"]
        assert doc["rank"] == 0 and doc["world"] == 1
    finally:
        coord.close()


def test_coordinator_purges_stale_collective_state():
    coord = Coordinator(lost_after_s=30.0).start()
    try:
        a = CoordinatorClient(coord.address, "a", rpc_timeout_s=5.0)
        a.join(expected=1, grace_s=5.0)
        # Completed barriers are kept only as a bounded tail.
        for s in range(20):
            a.barrier("b", step=s, timeout_s=5.0)
        with coord._cond:
            assert 0 < len(coord._barriers) <= 8
        # Plant an abandoned old-gen contribution (a worker that died
        # mid-allreduce); any generation bump must purge it and every
        # old-gen barrier set.
        with coord._cond:
            coord._contribs[(a.gen, 99, "orphan")] = {"a": {}}
        b = CoordinatorClient(coord.address, "b", rpc_timeout_s=5.0)
        b.join(expected=None, grace_s=5.0)  # gen bump
        with coord._cond:
            assert not coord._contribs
            assert not coord._barriers
    finally:
        coord.close()


def test_parse_address_portless():
    assert parse_address("myhost:1234") == ("myhost", 1234)
    assert parse_address(":1234") == ("127.0.0.1", 1234)
    assert parse_address("myhost") == ("myhost", 0)  # no ValueError
    c = CoordinatorClient("myhost", "w")  # parse-time must not raise
    assert (c.host, c.port) == ("myhost", 0)


# --------------------------------------------- ElasticTrainer, in-process

def test_elastic_single_process_train_and_resume(tmp_path):
    root = str(tmp_path / "ckpt")
    net = MultiLayerNetwork(make_conf()).init()
    tr = ElasticTrainer(ParallelWrapper(net, workers=1),
                        checkpoint_root=root, save_every=2,
                        fault_plan=FaultPlan())
    res = tr.run(shard_fn, steps=6)
    assert res.status == "finished" and res.step == 6 and res.restarts == 0
    assert tr.manager.all_steps() == [2, 4, 6]

    # A relaunched process resumes from the newest commit, not step 0.
    before = event_count("restore")
    net2 = MultiLayerNetwork(make_conf()).init()
    tr2 = ElasticTrainer(ParallelWrapper(net2, workers=1),
                         checkpoint_root=root, save_every=2,
                         fault_plan=FaultPlan())
    res2 = tr2.run(shard_fn, steps=8)
    assert res2.status == "finished" and res2.step == 8
    assert event_count("restore") >= before + 1
    assert_params_close(flat_params(net2), reference_params(8),
                        rtol=1e-6, atol=1e-9)


def test_elastic_iterator_data_fast_forwards_on_resume(tmp_path):
    root = str(tmp_path / "ckpt")
    batches = [full_batch(s) for s in range(8)]
    net = MultiLayerNetwork(make_conf()).init()
    tr = ElasticTrainer(ParallelWrapper(net, workers=1),
                        checkpoint_root=root, save_every=2,
                        fault_plan=FaultPlan())
    assert tr.run(ListDataSetIterator(batches, 16), steps=4).step == 4

    net2 = MultiLayerNetwork(make_conf()).init()
    tr2 = ElasticTrainer(ParallelWrapper(net2, workers=1),
                         checkpoint_root=root, save_every=2,
                         fault_plan=FaultPlan())
    res = tr2.run(ListDataSetIterator(batches, 16), steps=8)
    assert res.step == 8  # restored 4, fast-forwarded, trained 4..7
    assert_params_close(flat_params(net2), reference_params(8),
                        rtol=1e-6, atol=1e-9)


def test_position_stream_non_resettable_skips_only_delta():
    """On an in-run restart the shared iterator is already partially
    consumed; a non-resettable stream must skip only the delta to the
    restored step — not `restored_step` MORE batches from the current
    position (silent training-data loss on every recovery)."""
    net = MultiLayerNetwork(make_conf()).init()
    tr = ElasticTrainer(ParallelWrapper(net, workers=1),
                        fault_plan=FaultPlan())
    gen = iter([full_batch(s) for s in range(8)])  # no reset()

    stream = tr._position_stream(gen, 2)  # fresh start restored at step 2
    np.testing.assert_array_equal(next(stream).features,
                                  full_batch(2).features)
    tr._stream_pos += 1  # the train loop accounts for each draw

    # Restart restored at step 3 == current position: skip NOTHING.
    stream = tr._position_stream(gen, 3)
    np.testing.assert_array_equal(next(stream).features,
                                  full_batch(3).features)
    tr._stream_pos += 1

    # Restored step behind the live position: unreplayable -> warn,
    # continue from where the stream actually is.
    with pytest.warns(RuntimeWarning, match="not resettable"):
        stream = tr._position_stream(gen, 1)
    np.testing.assert_array_equal(next(stream).features,
                                  full_batch(4).features)


def test_coordinator_error_is_recoverable(monkeypatch):
    """An error document from the coordinator (e.g. a transient
    membership-shape failure) must consume the restart budget, not kill
    the run outright."""
    net = MultiLayerNetwork(make_conf()).init()
    tr = ElasticTrainer(ParallelWrapper(net, workers=1),
                        fault_plan=FaultPlan(), max_restarts=2)
    real = tr._train
    calls = {"n": 0}

    def flaky(data, steps, result):
        calls["n"] += 1
        if calls["n"] == 1:
            raise CoordinatorError("coordinator error: ValueError: boom")
        return real(data, steps, result)

    monkeypatch.setattr(tr, "_train", flaky)
    res = tr.run(shard_fn, steps=3)
    assert res.status == "finished" and res.step == 3
    assert res.restarts == 1


def test_elastic_sigterm_preempt_checkpoints_and_exits(tmp_path, monkeypatch):
    from deeplearning4j_tpu.observability import flight

    monkeypatch.setattr(flight, "dump_dir", str(tmp_path / "flight"))
    root = str(tmp_path / "ckpt")
    net = MultiLayerNetwork(make_conf()).init()
    plan = FaultPlan.from_json('[{"kind": "preempt", "step": 2}]')
    tr = ElasticTrainer(ParallelWrapper(net, workers=1),
                        checkpoint_root=root, save_every=0,  # only the
                        fault_plan=plan)                     # preempt save
    before = event_count("preempt")
    res = tr.run(shard_fn, steps=6)
    assert res.status == "preempted" and res.step == 2
    assert res.checkpoint and res.checkpoint.endswith("step_00000002")
    assert tr.manager.all_steps() == [2]  # exactly one committed step
    assert event_count("preempt") == before + 1
    # The run's SIGTERM handler was uninstalled on exit.
    import signal as _signal
    assert _signal.getsignal(_signal.SIGTERM) is tr._prev_sigterm \
        or tr._prev_sigterm is None


def test_elastic_two_worker_averaging_matches_single_machine(tmp_path):
    """The coordinator-transport equivalence (in threads): 2 workers,
    per-step parameter averaging == single-machine full-batch SGD."""
    addr = f"127.0.0.1:{_free_port()}"
    nets, errs = {}, []

    def worker(wid, host):
        try:
            net = MultiLayerNetwork(make_conf()).init()
            tr = ElasticTrainer(
                ParallelWrapper(net, workers=1), coordinator_address=addr,
                worker_id=wid, expected_world=2, host_coordinator=host,
                heartbeat_s=0.2, join_grace_s=20.0,
                collective_timeout_s=20.0, fault_plan=FaultPlan())
            res = tr.run(shard_fn, steps=5)
            assert res.status == "finished" and res.step == 5
            nets[wid] = net
        except Exception as e:  # surfaced by the main thread
            errs.append(e)

    ts = [threading.Thread(target=worker, args=("a", True), daemon=True),
          threading.Thread(target=worker, args=("b", False), daemon=True)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
        assert not t.is_alive(), "elastic worker thread hung"
    assert not errs, errs
    ref = reference_params(5)
    assert_params_close(flat_params(nets["a"]), ref)
    assert_params_close(flat_params(nets["b"]), ref)


# ------------------------------------------------- multi-process chaos CI

CHAOS_WORKER = """
import json, os, sys
wid = sys.argv[1]; addr = sys.argv[2]; root = sys.argv[3]; out = sys.argv[4]
is_host = sys.argv[5] == "host"
os.environ["DL4J_TPU_FLIGHT_DIR"] = os.path.join(root, "flight-" + wid)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

__CONF__

import numpy as np
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel.elastic import ElasticTrainer
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

def shard_fn(step, rank, world):
    X, Y = make_data(step)
    n = X.shape[0] // world
    return DataSet(X[rank*n:(rank+1)*n], Y[rank*n:(rank+1)*n])

net = MultiLayerNetwork(make_conf()).init()
trainer = ElasticTrainer(
    ParallelWrapper(net, workers=1),
    coordinator_address=addr, worker_id=wid, expected_world=2,
    checkpoint_root=os.path.join(root, "ckpt"), save_every=__SAVE_EVERY__,
    host_coordinator=is_host, heartbeat_s=0.25, join_grace_s=60.0,
    collective_timeout_s=20.0, lost_after_s=2.0)
if trainer.manager is not None:
    # Deterministic commit-before-fault ordering for the test schedule.
    trainer.manager.async_save = False
# Short RPC timeout so an injected coordinator hang forces visible
# backoff retries instead of hiding inside one long blocking read.
trainer.client.rpc_timeout_s = 1.0
result = trainer.run(shard_fn, steps=__STEPS__)

from deeplearning4j_tpu.observability.elastic import EVENTS
events = dict((e, EVENTS.labels(event=e).get())
              for e in ("preempt", "host_lost", "restart", "restore",
                        "restore_fallback", "coordinator_retry"))
params = dict()
for lk, layer in net.params_tree.items():
    for pk, v in layer.items():
        params[lk + "/" + pk] = np.asarray(v).tolist()
committed = trainer.manager.all_steps() if trainer.manager else []
flight_dir = os.environ["DL4J_TPU_FLIGHT_DIR"]
bundles = sorted(os.listdir(flight_dir)) if os.path.isdir(flight_dir) else []
with open(out, "w") as f:
    json.dump({"status": result.status, "step": result.step,
               "restarts": result.restarts,
               "recoveries_s": list(result.recoveries_s),
               "checkpoint": result.checkpoint, "committed": committed,
               "bundles": bundles, "events": events, "params": params}, f)
print("worker", wid, "done", flush=True)
"""


def _spawn_elastic_workers(tmp_path, plan, steps, save_every):
    addr = f"127.0.0.1:{_free_port()}"
    script = tmp_path / "worker.py"
    script.write_text(CHAOS_WORKER
                      .replace("__CONF__", CONF_CODE)
                      .replace("__SAVE_EVERY__", str(save_every))
                      .replace("__STEPS__", str(steps)))
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["DL4J_TPU_FAULT_PLAN"] = json.dumps(plan)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = []
    for wid, role in (("a", "host"), ("b", "peer")):
        procs.append(subprocess.Popen(
            [sys.executable, str(script), wid, addr, str(tmp_path),
             str(tmp_path / f"out-{wid}.json"), role],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, text=True))
    outputs = []
    try:
        for p in procs:
            outputs.append(p.communicate(timeout=300)[0])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outputs


def _load_out(tmp_path, wid):
    with open(tmp_path / f"out-{wid}.json") as f:
        return json.load(f)


def test_chaos_two_process_kill_hang_truncate_recovers(tmp_path):
    """The CI chaos drill (acceptance criteria): a 2-process run whose
    plan hangs the coordinator at step 1, truncates the newest committed
    checkpoint at step 7 (worker a) and kills worker b at step 7 must
    recover on the survivor — restore past the corrupt copy onto the
    re-formed world-1 cluster, finish all 10 steps, and land float-close
    to an unfaulted single-machine run of the same schedule — with every
    recovery event visible in dl4j_elastic_events_total."""
    steps = 10
    procs, outputs = _spawn_elastic_workers(
        tmp_path,
        plan=[
            {"kind": "hang_coordinator", "step": 1, "worker": 0,
             "seconds": 2.0},
            {"kind": "truncate_chunk", "step": 7, "worker": 0, "bytes": 64},
            {"kind": "kill", "step": 7, "worker": 1},
        ],
        steps=steps, save_every=2)
    assert procs[0].returncode == 0, f"survivor failed:\n{outputs[0][-3000:]}"
    assert procs[1].returncode == 137, \
        f"worker b should die by os._exit(137):\n{outputs[1][-3000:]}"

    got = _load_out(tmp_path, "a")
    assert got["status"] == "finished"
    assert got["step"] == steps
    assert got["restarts"] == 1
    assert len(got["recoveries_s"]) == 1 and got["recoveries_s"][0] > 0
    ev = got["events"]
    assert ev["host_lost"] >= 1, ev          # reaper evicted worker b
    assert ev["restart"] >= 1, ev            # supervisor re-entered join
    assert ev["restore"] >= 1, ev            # checkpoint restored
    assert ev["restore_fallback"] >= 1, ev   # corrupt newest skipped
    assert ev["coordinator_retry"] >= 1, ev  # hang survived via backoff
    assert ev["preempt"] == 0, ev
    # Float-close equivalence with the unfaulted run of the same schedule.
    assert_params_close(got["params"], reference_params(steps))


def test_preemption_forensics_two_process_then_resume(tmp_path):
    """Satellite contract: SIGTERM (via the fault plan's preempt) during a
    2-process run leaves EXACTLY one committed checkpoint and one flight
    bundle per process; a restarted cluster resumes at the checkpointed
    step and finishes float-close to an uninterrupted run."""
    steps = 6
    procs, outputs = _spawn_elastic_workers(
        tmp_path, plan=[{"kind": "preempt", "step": 3}],
        steps=steps, save_every=0)
    for p, text in zip(procs, outputs):
        assert p.returncode == 0, f"worker failed:\n{text[-3000:]}"
    for wid in ("a", "b"):
        got = _load_out(tmp_path, wid)
        assert got["status"] == "preempted", got
        assert got["step"] == 3
        assert got["committed"] == [3], \
            f"exactly one committed checkpoint expected: {got['committed']}"
        assert len(got["bundles"]) == 1, \
            f"exactly one flight bundle expected: {got['bundles']}"
        assert got["events"]["preempt"] == 1

    # Restart the cluster (in threads) on the same checkpoint root: both
    # workers must restore step 3 and finish the schedule.
    addr = f"127.0.0.1:{_free_port()}"
    nets, errs = {}, []

    def worker(wid, host):
        try:
            net = MultiLayerNetwork(make_conf()).init()
            tr = ElasticTrainer(
                ParallelWrapper(net, workers=1), coordinator_address=addr,
                worker_id=wid, expected_world=2, host_coordinator=host,
                checkpoint_root=str(tmp_path / "ckpt"), save_every=0,
                heartbeat_s=0.2, join_grace_s=20.0,
                collective_timeout_s=20.0, fault_plan=FaultPlan())
            res = tr.run(shard_fn, steps=steps)
            assert res.status == "finished" and res.step == steps
            nets[wid] = net
        except Exception as e:
            errs.append(e)

    before = event_count("restore")
    ts = [threading.Thread(target=worker, args=("a", True), daemon=True),
          threading.Thread(target=worker, args=("b", False), daemon=True)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
        assert not t.is_alive(), "resume worker thread hung"
    assert not errs, errs
    assert event_count("restore") >= before + 2  # both workers restored
    ref = reference_params(steps)
    assert_params_close(flat_params(nets["a"]), ref)
    assert_params_close(flat_params(nets["b"]), ref)
