"""Flash-attention kernel tests (Pallas interpret mode on the CPU mesh —
same kernel code the TPU runs compiled; PERF.md §6 has the on-chip
numbers)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops.flash_attention import flash_attention
from deeplearning4j_tpu.parallel.sequence import dense_attention


def qkv(rng, b=2, t=128, h=2, d=8, dtype="float32"):
    mk = lambda: rng.randn(b, t, h, d).astype(dtype)
    return jnp.asarray(mk()), jnp.asarray(mk()), jnp.asarray(mk())


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False], ids=["causal", "full"])
    def test_matches_dense(self, rng, causal):
        q, k, v = qkv(rng)
        got = flash_attention(q, k, v, causal, None, 64, 64)
        want = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)

    def test_uneven_length_falls_back(self, rng):
        q, k, v = qkv(rng, t=100)  # not a block multiple
        got = flash_attention(q, k, v, True, None, 64, 64)
        want = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)

    def test_grads_match_dense(self, rng):
        q, k, v = qkv(rng, t=64)
        w = jnp.asarray(rng.randn(*q.shape).astype("float32"))
        g_f = jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, True, None, 64, 64) * w),
            argnums=(0, 1, 2))(q, k, v)
        g_d = jax.grad(lambda q, k, v: jnp.sum(
            dense_attention(q, k, v, causal=True) * w),
            argnums=(0, 1, 2))(q, k, v)
        for gf, gd in zip(g_f, g_d):
            np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                       rtol=2e-4, atol=2e-5)

    def test_jit_composes(self, rng):
        q, k, v = qkv(rng, t=64)
        f = jax.jit(lambda q, k, v: flash_attention(q, k, v, True, None,
                                                    64, 64))
        np.testing.assert_allclose(
            np.asarray(f(q, k, v)),
            np.asarray(dense_attention(q, k, v, causal=True)),
            rtol=2e-5, atol=2e-6)

    def test_streaming_path_matches_dense(self, rng, monkeypatch):
        # Force the long-T streaming kernel (k-blocks innermost, scratch
        # accumulators) even at test size.
        from deeplearning4j_tpu.ops import flash_attention as fa

        monkeypatch.setattr(fa, "_RESIDENT_KV_LIMIT", 0)
        # t=192 is used by no other test: the jitted wrapper reads the
        # limit at TRACE time, so a shape another test already compiled
        # would silently reuse the resident-path executable.
        q, k, v = qkv(rng, t=192)
        for causal in (True, False):
            got = fa.flash_attention(q, k, v, causal, None, 64, 64)
            want = dense_attention(q, k, v, causal=causal)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=2e-5, atol=2e-6)

    def test_framework_attention_entry(self, rng):
        # parallel.sequence.attention is the public entry; impl="auto"
        # routes to the Pallas kernel, impl="dense" to the XLA oracle.
        from deeplearning4j_tpu.parallel.sequence import attention

        q, k, v = qkv(rng, t=64)
        np.testing.assert_allclose(
            np.asarray(attention(q, k, v)),
            np.asarray(attention(q, k, v, impl="dense")),
            rtol=2e-5, atol=2e-6)

    @pytest.mark.parametrize("causal", [True, False], ids=["causal", "full"])
    def test_pallas_backward_matches_dense(self, rng, causal):
        # t=256/blocks 64 is resident-eligible: grads flow through the
        # Pallas dq/dkv kernels (lse saved by the fwd), not the XLA VJP.
        q, k, v = qkv(rng, t=256, h=2, d=8)
        w = jnp.asarray(rng.randn(*q.shape).astype("float32"))
        g_f = jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, causal, None, 64, 64) * w),
            argnums=(0, 1, 2))(q, k, v)
        g_d = jax.grad(lambda q, k, v: jnp.sum(
            dense_attention(q, k, v, causal=causal) * w),
            argnums=(0, 1, 2))(q, k, v)
        for gf, gd in zip(g_f, g_d):
            np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                       rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("causal", [True, False], ids=["causal", "full"])
    def test_streaming_backward_matches_dense(self, rng, monkeypatch, causal):
        # Beyond the resident limit the backward is STILL Pallas: the
        # streaming dq/dkv kernels walk the scalar-prefetched block
        # sequences (triangular when causal) with O(block) scratch — no
        # [T, T] matrix exists in fwd or bwd (round-5 long-T training path).
        from deeplearning4j_tpu.ops import flash_attention as fa

        monkeypatch.setattr(fa, "_RESIDENT_KV_LIMIT", 0)
        t = 320 if causal else 384  # unique shapes: fresh traces
        q, k, v = qkv(rng, t=t, h=1, d=4)
        w = jnp.asarray(rng.randn(*q.shape).astype("float32"))
        g_f = jax.grad(lambda q, k, v: jnp.sum(
            fa.flash_attention(q, k, v, causal, None, 64, 64) * w),
            argnums=(0, 1, 2))(q, k, v)
        g_d = jax.grad(lambda q, k, v: jnp.sum(
            dense_attention(q, k, v, causal=causal) * w),
            argnums=(0, 1, 2))(q, k, v)
        for gf, gd in zip(g_f, g_d):
            np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                       rtol=2e-4, atol=2e-5)

    def test_nonmultiple_T_backward_falls_back(self, rng):
        # Only a non-block-multiple T still uses the XLA dense VJP.
        q, k, v = qkv(rng, t=100, h=1, d=4)
        w = jnp.asarray(rng.randn(*q.shape).astype("float32"))
        g_f = jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, True, None, 64, 64) * w),
            argnums=(0, 1, 2))(q, k, v)
        g_d = jax.grad(lambda q, k, v: jnp.sum(
            dense_attention(q, k, v, causal=True) * w),
            argnums=(0, 1, 2))(q, k, v)
        for gf, gd in zip(g_f, g_d):
            np.testing.assert_allclose(np.asarray(gf), np.asarray(gd),
                                       rtol=2e-4, atol=2e-5)

    def test_long_T_training_never_materializes_quadratic(self, rng,
                                                          monkeypatch):
        # A training step THROUGH the engine's attention layer at a T
        # beyond the (patched) resident limit: loss + grads finite via the
        # streaming Pallas fwd/bwd. Structural guarantee: those kernels
        # only allocate [block, block] tiles, so peak memory is O(T·D).
        from deeplearning4j_tpu.ops import flash_attention as fa

        monkeypatch.setattr(fa, "_RESIDENT_KV_LIMIT", 1024)
        q, k, v = qkv(rng, t=448, h=1, d=8)  # unique shape: fresh trace
        loss, grads = jax.value_and_grad(
            lambda q, k, v: jnp.mean(
                fa.flash_attention(q, k, v, True, None, 64, 64) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        assert np.isfinite(float(loss))
        assert all(np.all(np.isfinite(np.asarray(g))) for g in grads)
