"""MultiLayerNetwork end-to-end tests (reference analog:
`deeplearning4j-core/src/test/.../nn/multilayer/MultiLayerTest.java`,
`BackPropMLPTest.java`)."""

import numpy as np
import pytest

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer,
    BatchNormalization,
    DenseLayer,
    DropoutLayer,
    EmbeddingLayer,
    OutputLayer,
)
from deeplearning4j_tpu.nn.conf.neural_net import MultiLayerConfiguration

from conftest import make_classification_data


def mlp_conf(n_in=4, n_out=3, updater="sgd", lr=0.5, **kw):
    return (NeuralNetConfiguration.builder()
            .seed(42).learning_rate(lr).updater(updater)
            .weight_init("xavier")
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=n_out, activation="softmax", loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(n_in))
            .build())


class TestBuilder:
    def test_n_in_inference(self):
        conf = mlp_conf()
        assert conf.layers[0].n_in == 4
        assert conf.layers[1].n_in == 16

    def test_global_defaults_merged(self):
        conf = mlp_conf(updater="adam", lr=0.01)
        assert conf.layers[0].updater == "adam"
        assert conf.layers[0].learning_rate == 0.01
        # per-layer override wins
        conf2 = (NeuralNetConfiguration.builder().learning_rate(0.5)
                 .list()
                 .layer(DenseLayer(n_in=4, n_out=2, learning_rate=0.125))
                 .layer(OutputLayer(n_out=2))
                 .build())
        assert conf2.layers[0].learning_rate == 0.125
        assert conf2.layers[1].learning_rate == 0.5

    def test_json_roundtrip(self):
        conf = mlp_conf(updater="adam")
        conf2 = MultiLayerConfiguration.from_json(conf.to_json())
        assert conf2.to_json() == conf.to_json()
        assert conf2.layers[0].n_in == 4
        assert type(conf2.layers[0]).__name__ == "DenseLayer"

    def test_yaml_roundtrip(self):
        """Reference parity: `MultiLayerConfiguration.toYaml/fromYaml`
        (`NeuralNetConfiguration.java:295-340`) — same payload as JSON, and
        a YAML-restored config must train-compatibly equal the original."""
        conf = mlp_conf(updater="adam")
        conf2 = MultiLayerConfiguration.from_yaml(conf.to_yaml())
        assert conf2.to_json() == conf.to_json()
        net = MultiLayerNetwork(conf2).init()
        assert net.num_params() > 0

    def test_layer_indexing_styles(self):
        c1 = (NeuralNetConfiguration.builder().list()
              .layer(0, DenseLayer(n_in=4, n_out=8))
              .layer(1, OutputLayer(n_out=3))
              .build())
        assert len(c1.layers) == 2


class TestTraining:
    def test_mlp_learns_linearly_separable(self, rng):
        X, Y = make_classification_data(rng)
        net = MultiLayerNetwork(mlp_conf(updater="adam", lr=0.05)).init()
        ds = DataSet(X, Y)
        s0 = net.score(ds)
        for _ in range(150):
            net.fit(ds)
        assert net.score(ds) < s0 * 0.5
        assert net.evaluate(ds).accuracy() > 0.9

    def test_score_decreases_all_updaters(self, rng):
        X, Y = make_classification_data(rng)
        ds = DataSet(X, Y)
        for upd in ["sgd", "adam", "nesterovs", "rmsprop", "adagrad"]:
            net = MultiLayerNetwork(mlp_conf(updater=upd, lr=0.01)).init()
            s0 = net.score(ds)
            for _ in range(30):
                net.fit(ds)
            assert net.score(ds) < s0, upd

    def test_fit_xy_and_dataset_equivalent(self, rng):
        X, Y = make_classification_data(rng)
        n1 = MultiLayerNetwork(mlp_conf()).init()
        n2 = MultiLayerNetwork(mlp_conf()).init()
        n1.fit(X, Y)
        n2.fit(DataSet(X, Y))
        np.testing.assert_allclose(n1.params(), n2.params(), rtol=1e-6)

    def test_output_shape_and_softmax(self, rng):
        X, Y = make_classification_data(rng)
        net = MultiLayerNetwork(mlp_conf()).init()
        out = net.output(X)
        assert out.shape == (64, 3)
        np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)

    def test_predict(self, rng):
        X, Y = make_classification_data(rng)
        net = MultiLayerNetwork(mlp_conf()).init()
        assert net.predict(X).shape == (64,)

    def test_feed_forward_collects_all(self, rng):
        X, Y = make_classification_data(rng)
        net = MultiLayerNetwork(mlp_conf()).init()
        acts = net.feed_forward(X)
        assert len(acts) == 2
        assert acts[0].shape == (64, 16)
        assert acts[1].shape == (64, 3)

    def test_iterations_hyperparam(self, rng):
        X, Y = make_classification_data(rng)
        conf = (NeuralNetConfiguration.builder().seed(1).learning_rate(0.1)
                .iterations(5).list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        net.fit(DataSet(X, Y))
        assert net.iteration == 5


class TestParamsView:
    def test_flat_roundtrip(self, rng):
        net = MultiLayerNetwork(mlp_conf()).init()
        flat = net.params()
        assert flat.shape == (net.num_params(),)
        flat2 = flat * 2
        net.set_params(flat2)
        np.testing.assert_allclose(net.params(), flat2, rtol=1e-6)

    def test_num_params(self):
        net = MultiLayerNetwork(mlp_conf()).init()
        assert net.num_params() == 4 * 16 + 16 + 16 * 3 + 3

    def test_updater_state_roundtrip(self, rng):
        X, Y = make_classification_data(rng)
        net = MultiLayerNetwork(mlp_conf(updater="adam")).init()
        net.fit(X, Y)
        st = net.updater_state_flat()
        assert st.size > 0
        net.set_updater_state_flat(st * 0.5)
        np.testing.assert_allclose(net.updater_state_flat(), st * 0.5, rtol=1e-6)


class TestRegularization:
    def test_l2_shrinks_weights(self, rng):
        X, Y = make_classification_data(rng)
        ds = DataSet(X, Y)
        nets = {}
        for l2 in [0.0, 0.5]:
            conf = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.1)
                    .l2(l2).list()
                    .layer(DenseLayer(n_out=16, activation="tanh"))
                    .layer(OutputLayer(n_out=3, activation="softmax"))
                    .set_input_type(InputType.feed_forward(4)).build())
            net = MultiLayerNetwork(conf).init()
            for _ in range(100):
                net.fit(ds)
            nets[l2] = np.linalg.norm(net.params())
        assert nets[0.5] < nets[0.0]

    def test_l1_l2_in_score(self, rng):
        X, Y = make_classification_data(rng)
        c0 = mlp_conf()
        net0 = MultiLayerNetwork(c0).init()
        s_plain = net0.score(DataSet(X, Y))
        conf = (NeuralNetConfiguration.builder().seed(42).learning_rate(0.5)
                .weight_init("xavier").l2(1.0).list()
                .layer(DenseLayer(n_out=16, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax", loss_function="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        net1 = MultiLayerNetwork(conf).init(params=net0.params_tree)
        assert net1.score(DataSet(X, Y)) > s_plain

    def test_dropout_train_only(self, rng):
        X, Y = make_classification_data(rng)
        conf = (NeuralNetConfiguration.builder().seed(3).drop_out(0.5).list()
                .layer(DenseLayer(n_out=32, activation="relu"))
                .layer(OutputLayer(n_out=3, activation="softmax"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        o1 = net.output(X)
        o2 = net.output(X)
        np.testing.assert_array_equal(o1, o2)  # inference is deterministic


class TestBatchNorm:
    def test_bn_running_stats_update(self, rng):
        X, Y = make_classification_data(rng)
        conf = (NeuralNetConfiguration.builder().seed(5).learning_rate(0.05)
                .activation("identity").list()
                .layer(DenseLayer(n_out=8, activation="relu"))
                .layer(BatchNormalization())
                .layer(OutputLayer(n_out=3, activation="softmax"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        m0 = np.asarray(net.state["layer_1"]["mean"]).copy()
        net.fit(DataSet(X, Y))
        m1 = np.asarray(net.state["layer_1"]["mean"])
        assert not np.allclose(m0, m1)
        for _ in range(50):
            net.fit(DataSet(X, Y))
        assert net.evaluate(DataSet(X, Y)).accuracy() > 0.8


class TestEmbedding:
    def test_embedding_lookup(self, rng):
        idx = rng.randint(0, 10, size=(32,))
        Y = np.eye(3)[idx % 3].astype("float64")
        conf = (NeuralNetConfiguration.builder().seed(9).learning_rate(0.5)
                .list()
                .layer(EmbeddingLayer(n_in=10, n_out=8, activation="identity"))
                .layer(OutputLayer(n_out=3, activation="softmax"))
                .build())
        net = MultiLayerNetwork(conf).init()
        out = net.output(idx.astype("int32"))
        assert out.shape == (32, 3)
        for _ in range(100):
            net.fit(idx.astype("int32"), Y)
        assert net.evaluate(DataSet(idx.astype("int32"), Y)).accuracy() > 0.9


class TestTbpttScanPath:
    def test_scan_path_matches_per_chunk_path(self, rng):
        """The fused one-dispatch tBPTT scan (default) and the per-chunk
        stats path must produce identical training numerics — same chunk
        boundaries, same RNG split chain — including a NON-multiple sequence
        length (t=25, fwd=10 → remainder chunk of 5 at its true length, no
        padding) with dropout active and a label mask."""
        from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer

        b, t, f, c = 6, 25, 4, 3
        X = rng.randn(b, t, f).astype("float32")
        Y = np.eye(c)[rng.randint(0, c, (b, t))].astype("float32")
        lmask = np.ones((b, t), "float32")
        lmask[0, 7:] = 0.0

        def conf_fn():
            return (NeuralNetConfiguration.builder()
                    .seed(7).learning_rate(0.05).updater("sgd")
                    .weight_init("xavier")
                    .list()
                    .layer(GravesLSTM(n_out=6, activation="tanh", dropout=0.3))
                    .layer(RnnOutputLayer(n_out=c, activation="softmax",
                                          loss_function="mcxent"))
                    .set_input_type(InputType.recurrent(f))
                    .backprop_type("truncatedbptt")
                    .t_bptt_forward_length(10)
                    .build())

        ds = DataSet(X, Y, None, lmask)
        fast = MultiLayerNetwork(conf_fn()).init()
        fast.fit(ds)
        assert fast.iteration == 1
        slow = MultiLayerNetwork(conf_fn()).init()
        slow._collect_stats = True  # forces the per-chunk dispatch path
        slow.fit(ds)
        assert slow.iteration == 1
        np.testing.assert_allclose(fast.params(), slow.params(),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(float(fast.score_value),
                                   float(slow.score_value), rtol=1e-5)

    def test_graph_scan_path_matches_per_chunk_path(self, rng):
        """Same equivalence for the ComputationGraph engine — non-multiple
        t, dropout, label mask, plus a STATIC second input (must pass
        through the time chunking untouched)."""
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        from deeplearning4j_tpu.nn.conf.layers import (
            DenseLayer, GravesLSTM, RnnOutputLayer,
        )
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        b, t, f, c = 4, 25, 3, 3
        X = rng.randn(b, t, f).astype("float32")
        S = rng.randn(b, 5).astype("float32")  # static side input
        Y = np.eye(c)[rng.randint(0, c, (b, t))].astype("float32")
        lmask = np.ones((b, t), "float32")
        lmask[0, 9:] = 0.0

        def conf_fn():
            gb = (NeuralNetConfiguration.builder()
                  .seed(11).learning_rate(0.05).updater("sgd")
                  .weight_init("xavier")
                  .graph_builder()
                  .add_inputs("seq", "static")
                  .add_layer("lstm", GravesLSTM(n_out=6, activation="tanh",
                                                dropout=0.3), "seq")
                  .add_layer("emb", DenseLayer(n_out=6, activation="tanh"),
                             "static")
                  .add_layer("out", RnnOutputLayer(
                      n_out=c, activation="softmax",
                      loss_function="mcxent"), "lstm")
                  .set_outputs("out"))
            gb.set_input_types(InputType.recurrent(f),
                               InputType.feed_forward(5))
            conf = gb.build()
            conf.backprop_type = "truncatedbptt"
            conf.tbptt_fwd_length = 10
            return conf

        mds = MultiDataSet(features=[X, S], labels=[Y],
                           labels_masks=[lmask])
        fast = ComputationGraph(conf_fn()).init()
        fast.fit(mds)
        assert fast.iteration == 1
        slow = ComputationGraph(conf_fn()).init()
        slow._collect_stats = True
        slow.fit(mds)
        np.testing.assert_allclose(fast.params(), slow.params(),
                                   rtol=1e-5, atol=1e-6)


class TestZooModels:
    def test_alexnet_builds_and_steps(self, rng):
        """AlexNet (the reference LRN layer's raison d'etre) builds, runs a
        small-image forward + one train step."""
        from deeplearning4j_tpu.models import zoo

        conf = zoo.alexnet(n_classes=10, image=67, dtype="float32")
        net = MultiLayerNetwork(conf).init()
        X = rng.rand(2, 67, 67, 3).astype("float32")
        Y = np.eye(10, dtype="float32")[rng.randint(0, 10, 2)]
        out = net.output(X)
        assert out.shape == (2, 10)
        np.testing.assert_allclose(np.asarray(out).sum(-1), 1.0, rtol=1e-4)
        net.fit(DataSet(X, Y))
        assert np.isfinite(net.score_value)


class TestVaeLossFunctionWrapper:
    """Reference parity: `variational/LossFunctionWrapper.java` — any
    ILossFunction as the VAE reconstruction distribution."""

    def test_sizes_and_per_example_score(self, rng):
        from deeplearning4j_tpu.nn.layers.variational import (
            dist_input_size, neg_log_prob,
        )

        assert dist_input_size(("loss", "mse", "sigmoid"), 7) == 7
        assert dist_input_size([["loss", "mse"], "bernoulli"][0], 4) == 4
        import jax.numpy as jnp

        x = jnp.asarray(rng.rand(5, 3))
        pre = jnp.asarray(rng.randn(5, 3))
        got = neg_log_prob(("loss", "mse", "identity"), x, pre)
        # MSE = feature-MEAN squared error (reference LossMSE semantics).
        want = jnp.mean((x - pre) ** 2, axis=-1)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6)
        # Composite with a wrapper entry.
        comp = [(("loss", "mse"), 2), ("bernoulli", 1)]
        assert dist_input_size(comp, 3) == 3
        got_c = neg_log_prob(comp, x, pre)
        assert got_c.shape == (5,)

    def test_vae_pretrains_with_wrapper(self, rng):
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import (
            OutputLayer, VariationalAutoencoder,
        )

        X = rng.rand(16, 6).astype("float64")
        conf = (NeuralNetConfiguration.builder()
                .seed(5).learning_rate(0.05).updater("adam").dtype("float64")
                .list()
                .layer(VariationalAutoencoder(
                    n_out=3, encoder_layer_sizes=(8,),
                    decoder_layer_sizes=(8,),
                    reconstruction_distribution=("loss", "mse", "sigmoid"),
                    activation="tanh"))
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss_function="mcxent"))
                .set_input_type(InputType.feed_forward(6))
                .build())
        net = MultiLayerNetwork(conf).init()
        losses = []
        for _ in range(25):
            net.pretrain(DataSet(X, None))
            losses.append(float(net.score_value))
        assert losses[-1] < losses[0]
        # JSON round trip keeps the wrapper spec.
        from deeplearning4j_tpu.nn.conf.neural_net import MultiLayerConfiguration
        back = MultiLayerConfiguration.from_json(conf.to_json())
        rd = back.layers[0].reconstruction_distribution
        assert list(rd)[:2] == ["loss", "mse"]


class TestUint8DeviceScaling:
    """uint8 features auto-scale 0-255 -> 0-1 ON DEVICE (the TPU-native
    ImagePreProcessingScaler: ship bytes, normalize in-jit — PERF.md §3)."""

    def test_output_and_training_match_prescaled(self, rng):
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer

        conf = (NeuralNetConfiguration.builder()
                .seed(5).learning_rate(0.05).updater("sgd")
                .list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss_function="mcxent"))
                .set_input_type(InputType.feed_forward(6))
                .build())
        Xb = rng.randint(0, 256, (10, 6)).astype(np.uint8)
        Xf = Xb.astype("float32") / 255.0
        Y = np.eye(3)[rng.randint(0, 3, 10)].astype("float32")

        net_b = MultiLayerNetwork(conf).init()
        net_f = net_b.clone()
        np.testing.assert_allclose(net_b.output(Xb), net_f.output(Xf),
                                   rtol=1e-6, atol=1e-7)
        for _ in range(3):
            net_b.fit(DataSet(Xb, Y))
            net_f.fit(DataSet(Xf, Y))
        for lk in net_b.params_tree:
            for pk in net_b.params_tree[lk]:
                np.testing.assert_allclose(
                    np.asarray(net_b.params_tree[lk][pk]),
                    np.asarray(net_f.params_tree[lk][pk]),
                    rtol=1e-5, atol=1e-6)

    def test_graph_engine_too(self, rng):
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        gb = (NeuralNetConfiguration.builder()
              .seed(5).learning_rate(0.05).updater("sgd")
              .graph_builder()
              .add_inputs("in")
              .add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
              .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                            loss_function="mcxent"), "d")
              .set_outputs("out"))
        gb.set_input_types(InputType.feed_forward(6))
        cg = ComputationGraph(gb.build()).init()
        Xb = rng.randint(0, 256, (10, 6)).astype(np.uint8)
        Xf = Xb.astype("float32") / 255.0
        np.testing.assert_allclose(cg.output_single(Xb),
                                   cg.output_single(Xf),
                                   rtol=1e-6, atol=1e-7)


class TestSparseLabels:
    """Integer class-id labels ([b] / [b, t]) for the cross-entropy
    losses — a TPU-native extension past the reference's one-hot-only
    contract (at LM vocab sizes the one-hot tensor dominates the batch
    payload). Must train bit-identically to one-hot."""

    def test_mlp_sparse_equals_onehot(self, rng):
        X, Y = make_classification_data(rng)
        ids = Y.argmax(-1).astype(np.int32)
        n1 = MultiLayerNetwork(mlp_conf(updater="adam", lr=0.05)).init()
        n2 = MultiLayerNetwork(mlp_conf(updater="adam", lr=0.05)).init()
        for _ in range(5):
            n1.fit(DataSet(X, Y))
            n2.fit(DataSet(X, ids))
        np.testing.assert_allclose(n1.params(), n2.params(), rtol=1e-5)
        assert abs(n1.score(DataSet(X, Y)) - n2.score(DataSet(X, ids))) < 1e-5
        # Evaluation accepts ids too.
        assert n2.evaluate(DataSet(X, ids)).accuracy() == \
            n1.evaluate(DataSet(X, Y)).accuracy()

    def test_rnn_sequence_sparse_equals_onehot(self, rng):
        from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer

        conf_b = (NeuralNetConfiguration.builder()
                  .seed(3).learning_rate(0.1).updater("sgd")
                  .list()
                  .layer(GravesLSTM(n_out=8, activation="tanh"))
                  .layer(RnnOutputLayer(n_out=4, activation="softmax",
                                        loss_function="mcxent"))
                  .set_input_type(InputType.recurrent(5, 6)))
        X = rng.randn(3, 6, 5).astype("float32")
        ids = rng.randint(0, 4, (3, 6)).astype(np.int32)
        Y = np.eye(4, dtype="float32")[ids]
        n1 = MultiLayerNetwork(conf_b.build()).init()
        n2 = n1.clone()
        n1.fit(DataSet(X, Y))
        n2.fit(DataSet(X, ids))
        np.testing.assert_allclose(n1.params(), n2.params(), rtol=1e-5)

    def test_sparse_rejected_for_non_xent(self, rng):
        from deeplearning4j_tpu.nn import losses

        with pytest.raises(ValueError, match="integer class-id"):
            losses.score("mse", np.zeros(4, np.int32), np.zeros((4, 3)),
                         "identity")

    def test_transformer_trains_on_sparse_ids(self, rng):
        """The motivating case: LM training feeds [B, T] ids directly."""
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        from deeplearning4j_tpu.models.zoo import transformer_lm
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        v, t = 12, 10
        cg = ComputationGraph(transformer_lm(
            vocab_size=v, t=t, d_model=16, n_heads=2, n_blocks=1)).init()
        idx = rng.randint(0, v, (4, t))
        mds = MultiDataSet(
            features=[idx.astype("float32")],
            labels=[np.roll(idx, -1, axis=1).astype(np.int32)])
        s0 = cg.score(mds)
        for _ in range(20):
            cg.fit(mds)
        assert cg.score(mds) < s0


class TestSparseLabelsReviewFixes:
    def test_eval_sparse_no_giant_expansion_and_range_check(self, rng):
        """Sparse eval uses ids directly (no np.eye(V)); out-of-range ids
        fail loudly."""
        from deeplearning4j_tpu.eval.evaluation import Evaluation

        V = 50000
        ids = rng.randint(0, V, (4, 6))
        preds = rng.rand(4, 6, V).astype("float32")
        ev = Evaluation()
        ev.eval(ids, preds)  # must complete without a [V, V] eye
        assert ev.total == 24
        with pytest.raises(ValueError, match="class ids"):
            Evaluation().eval(np.asarray([V + 1]), rng.rand(1, V))

    def test_sharded_evaluate_sparse_matches_host(self, rng):
        from deeplearning4j_tpu.parallel import mesh as mesh_mod
        from deeplearning4j_tpu.parallel.evaluation import sharded_evaluate

        net = MultiLayerNetwork(mlp_conf()).init()
        X, Y = make_classification_data(rng)
        ids = Y.argmax(-1).astype(np.int32)
        host = net.evaluate(DataSet(X, ids))
        sharded = sharded_evaluate(net, DataSet(X, ids),
                                   mesh=mesh_mod.create_mesh((4,)))
        assert sharded.accuracy() == host.accuracy()
        np.testing.assert_array_equal(sharded.confusion.matrix,
                                      host.confusion.matrix)

    def test_graph_tbptt_sparse_equals_onehot(self, rng):
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        from deeplearning4j_tpu.nn.conf.layers import (
            GravesLSTM, RnnOutputLayer,
        )
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        def make():
            gb = (NeuralNetConfiguration.builder()
                  .seed(2).learning_rate(0.1).updater("sgd")
                  .graph_builder()
                  .add_inputs("in")
                  .add_layer("l", GravesLSTM(n_out=8, activation="tanh"),
                             "in")
                  .add_layer("out", RnnOutputLayer(
                      n_out=4, activation="softmax",
                      loss_function="mcxent"), "l")
                  .set_outputs("out"))
            gb.set_input_types(InputType.recurrent(5, 12))
            gb.backprop_type("truncatedbptt")
            gb.t_bptt_forward_length(4).t_bptt_backward_length(4)
            return ComputationGraph(gb.build()).init()

        X = rng.randn(3, 12, 5).astype("float32")
        ids = rng.randint(0, 4, (3, 12)).astype(np.int32)
        Y = np.eye(4, dtype="float32")[ids]
        g1, g2 = make(), make()
        g1.fit(MultiDataSet(features=[X], labels=[Y]))
        g2.fit(MultiDataSet(features=[X], labels=[ids]))
        for lk in g1.params_tree:
            for pk in g1.params_tree[lk]:
                np.testing.assert_allclose(
                    np.asarray(g1.params_tree[lk][pk]),
                    np.asarray(g2.params_tree[lk][pk]), rtol=1e-5)

    def test_ragged_batch_integer_onehot_still_works(self, rng):
        """Integer-dtype ONE-HOT labels through ParallelWrapper's padding
        (the ambiguity case): per-example mask, correct loss."""
        from deeplearning4j_tpu.parallel import mesh as mesh_mod
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

        net = MultiLayerNetwork(mlp_conf(updater="sgd", lr=0.1)).init()
        X, Y = make_classification_data(rng, n=13)  # ragged vs 4 devices
        Y_int = Y.astype(np.int32)
        pw = ParallelWrapper(net, mesh=mesh_mod.create_mesh((4,)))
        pw.fit(DataSet(X, Y_int))
        assert np.isfinite(net.score_value)

    def test_ragged_batch_sparse_sequence_ids(self, rng):
        """Sparse [b, t] ids through the wrapper's padding on an RNN net."""
        from deeplearning4j_tpu.nn.conf.layers import (
            GravesLSTM, RnnOutputLayer,
        )
        from deeplearning4j_tpu.parallel import mesh as mesh_mod
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

        conf = (NeuralNetConfiguration.builder()
                .seed(2).learning_rate(0.1).updater("sgd")
                .list()
                .layer(GravesLSTM(n_out=8, activation="tanh"))
                .layer(RnnOutputLayer(n_out=4, activation="softmax",
                                      loss_function="mcxent"))
                .set_input_type(InputType.recurrent(5, 6))
                .build())
        net = MultiLayerNetwork(conf).init()
        X = rng.randn(5, 6, 5).astype("float32")  # ragged vs 4 devices
        ids = rng.randint(0, 4, (5, 6)).astype(np.int32)
        pw = ParallelWrapper(net, mesh=mesh_mod.create_mesh((4,)))
        pw.fit(DataSet(X, ids))
        assert np.isfinite(net.score_value)
