"""Keras HDF5 import tests.

Reference analog: `deeplearning4j-modelimport/src/test/.../KerasModelEndToEndTest.java:42-52`
— golden-file testing with stored inputs/outputs. Most fixtures are written
in-test with h5py in the exact Keras 1.x on-disk format
(model_config/training_config attrs + per-layer weight groups) with
expected activations computed in plain numpy; `TestRealKerasGoldenFile`
additionally validates against a model file written by REAL Keras 1.1.2
(the reference repo's theano_mnist test resource), which is what caught
the Theano kernel-flip and channel-first-flatten semantics.
"""

import json
import os

import numpy as np
import pytest

h5py = pytest.importorskip("h5py")

from deeplearning4j_tpu.keras.import_model import (
    KerasImportException,
    KerasModelImport,
    import_keras_model_and_weights,
    import_keras_sequential_model_and_weights,
)


def write_keras_h5(path, model_config, weights, training_config=None,
                   keras_version=None):
    """Write a Keras-1-format model file: config attrs + weight groups."""
    with h5py.File(path, "w") as f:
        f.attrs["model_config"] = json.dumps(model_config).encode()
        if training_config is not None:
            f.attrs["training_config"] = json.dumps(training_config).encode()
        if keras_version is not None:
            f.attrs["keras_version"] = keras_version.encode()
        root = f.create_group("model_weights")
        for layer_name, wlist in weights.items():
            grp = root.create_group(layer_name)
            grp.attrs["weight_names"] = np.array(
                [n.encode() for n, _ in wlist])
            for n, arr in wlist:
                grp.create_dataset(n, data=np.asarray(arr, "float32"))


def seq_config(layers):
    return {"class_name": "Sequential", "config": layers}


TRAIN_CFG = {"loss": "categorical_crossentropy",
             "optimizer_config": {"config": {"lr": 0.01}}}


class TestSequentialMLP:
    def test_dense_golden_activations(self, tmp_path, rng):
        W1 = rng.randn(4, 5).astype("float32")
        b1 = rng.randn(5).astype("float32")
        W2 = rng.randn(5, 3).astype("float32")
        b2 = rng.randn(3).astype("float32")
        cfg = seq_config([
            {"class_name": "Dense",
             "config": {"name": "dense_1", "output_dim": 5,
                        "activation": "relu",
                        "batch_input_shape": [None, 4]}},
            {"class_name": "Dense",
             "config": {"name": "dense_2", "output_dim": 3,
                        "activation": "softmax"}},
        ])
        path = str(tmp_path / "mlp.h5")
        write_keras_h5(path, cfg, {
            "dense_1": [("dense_1_W", W1), ("dense_1_b", b1)],
            "dense_2": [("dense_2_W", W2), ("dense_2_b", b2)],
        }, TRAIN_CFG)

        net = import_keras_sequential_model_and_weights(path)
        x = rng.randn(6, 4).astype("float32")
        got = net.output(x)

        h = np.maximum(x @ W1 + b1, 0.0)
        logits = h @ W2 + b2
        e = np.exp(logits - logits.max(axis=1, keepdims=True))
        expect = e / e.sum(axis=1, keepdims=True)
        np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)

    def test_trainable_after_import(self, tmp_path, rng):
        """The compiled loss makes the imported net trainable (reference:
        enforceTrainingConfig path)."""
        W1 = rng.randn(4, 8).astype("float32")
        cfg = seq_config([
            {"class_name": "Dense",
             "config": {"name": "d1", "output_dim": 8, "activation": "tanh",
                        "batch_input_shape": [None, 4]}},
            {"class_name": "Dropout", "config": {"name": "drop", "p": 0.5}},
            {"class_name": "Dense",
             "config": {"name": "d2", "output_dim": 3,
                        "activation": "softmax"}},
        ])
        path = str(tmp_path / "train.h5")
        write_keras_h5(path, cfg, {
            "d1": [("d1_W", W1), ("d1_b", np.zeros(8))],
            "d2": [("d2_W", rng.randn(8, 3)), ("d2_b", np.zeros(3))],
        }, TRAIN_CFG)
        net = import_keras_sequential_model_and_weights(path)
        from deeplearning4j_tpu.datasets.dataset import DataSet
        X = rng.randn(16, 4).astype("float32")
        Y = np.eye(3)[rng.randint(0, 3, 16)].astype("float32")
        s0 = net.score(DataSet(X, Y))
        for _ in range(20):
            net.fit(X, Y)
        assert net.score(DataSet(X, Y)) < s0

    def test_dense_plus_activation_tail_trainable(self, tmp_path, rng):
        """Classic Keras pattern Dense(linear) -> Activation(softmax): the
        Activation tail becomes a param-free LossLayer so the import is
        trainable and the function unchanged."""
        W = rng.randn(4, 3).astype("float32")
        cfg = seq_config([
            {"class_name": "Dense",
             "config": {"name": "d", "output_dim": 3, "activation": "linear",
                        "batch_input_shape": [None, 4]}},
            {"class_name": "Activation",
             "config": {"name": "a", "activation": "softmax"}},
        ])
        path = str(tmp_path / "act_tail.h5")
        write_keras_h5(path, cfg, {"d": [("d_W", W), ("d_b", np.zeros(3))]},
                       TRAIN_CFG)
        net = import_keras_sequential_model_and_weights(path)
        x = rng.randn(5, 4).astype("float32")
        logits = x @ W
        e = np.exp(logits - logits.max(axis=1, keepdims=True))
        np.testing.assert_allclose(net.output(x),
                                   e / e.sum(axis=1, keepdims=True),
                                   rtol=1e-5, atol=1e-6)
        from deeplearning4j_tpu.datasets.dataset import DataSet
        Y = np.eye(3)[rng.randint(0, 3, 5)].astype("float32")
        s0 = net.score(DataSet(x, Y))
        for _ in range(10):
            net.fit(x, Y)
        assert net.score(DataSet(x, Y)) < s0

    def test_dispatch_facade(self, tmp_path, rng):
        cfg = seq_config([
            {"class_name": "Dense",
             "config": {"name": "d", "output_dim": 2, "activation": "softmax",
                        "batch_input_shape": [None, 3]}},
        ])
        path = str(tmp_path / "f.h5")
        write_keras_h5(path, cfg,
                       {"d": [("d_W", rng.randn(3, 2)), ("d_b", np.zeros(2))]})
        net = KerasModelImport.import_keras_model(path)
        assert net.output(rng.randn(2, 3).astype("float32")).shape == (2, 2)


def _conv2d_hwio(x, k, b, stride=(1, 1), pad=(0, 0)):
    """Tiny cross-correlation reference: x [n,h,w,cin], k [kh,kw,cin,cout]."""
    n, h, w, cin = x.shape
    kh, kw, _, cout = k.shape
    ph, pw = pad
    xp = np.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))
    oh = (h + 2 * ph - kh) // stride[0] + 1
    ow = (w + 2 * pw - kw) // stride[1] + 1
    out = np.zeros((n, oh, ow, cout), "float32")
    for i in range(oh):
        for j in range(ow):
            patch = xp[:, i * stride[0]:i * stride[0] + kh,
                       j * stride[1]:j * stride[1] + kw, :]
            out[:, i, j, :] = np.tensordot(patch, k, axes=([1, 2, 3], [0, 1, 2]))
    return out + b


class TestSequentialConv:
    def test_theano_kernel_transpose_and_padding_fold(self, tmp_path, rng):
        """th-ordered kernels [out,in,kh,kw] transpose to HWIO; a preceding
        ZeroPadding2D folds into the conv's padding (the VGG16 pattern)."""
        k_th = rng.randn(2, 1, 3, 3).astype("float32")  # [out,in,kh,kw]
        bc = rng.randn(2).astype("float32")
        Wd = rng.randn(2 * 4 * 4, 3).astype("float32")
        bd = rng.randn(3).astype("float32")
        cfg = seq_config([
            {"class_name": "ZeroPadding2D",
             "config": {"name": "pad", "padding": [1, 1],
                        "batch_input_shape": [None, 1, 8, 8],
                        "dim_ordering": "th"}},
            {"class_name": "Convolution2D",
             "config": {"name": "conv", "nb_filter": 2, "nb_row": 3,
                        "nb_col": 3, "subsample": [1, 1],
                        "border_mode": "valid", "dim_ordering": "th",
                        "activation": "relu"}},
            {"class_name": "MaxPooling2D",
             "config": {"name": "pool", "pool_size": [2, 2],
                        "strides": [2, 2], "border_mode": "valid",
                        "dim_ordering": "th"}},
            {"class_name": "Flatten", "config": {"name": "flat"}},
            {"class_name": "Dense",
             "config": {"name": "out", "output_dim": 3,
                        "activation": "softmax"}},
        ])
        path = str(tmp_path / "conv.h5")
        write_keras_h5(path, cfg, {
            "conv": [("conv_W", k_th), ("conv_b", bc)],
            "out": [("out_W", Wd), ("out_b", bd)],
        }, TRAIN_CFG)
        net = import_keras_sequential_model_and_weights(path)

        x = rng.randn(3, 8, 8, 1).astype("float32")  # framework layout NHWC
        got = net.output(x)

        # th kernels are 180°-flipped on import (Theano true-convolution
        # semantics; reference KerasConvolution.java:126-141).
        k = np.transpose(k_th[:, :, ::-1, ::-1], (2, 3, 1, 0))  # HWIO
        conv = np.maximum(_conv2d_hwio(x, k, bc, pad=(1, 1)), 0.0)  # 8x8x2
        pooled = conv.reshape(3, 4, 2, 4, 2, 2).max(axis=(2, 4))  # 4x4x2
        # th files index the flattened map channel-first: Wd's rows are in
        # [c, h, w] order (the importer permutes them to the framework's
        # NHWC flatten; here the reference computation flattens th-style).
        flat = np.transpose(pooled, (0, 3, 1, 2)).reshape(3, -1)
        logits = flat @ Wd + bd
        e = np.exp(logits - logits.max(axis=1, keepdims=True))
        expect = e / e.sum(axis=1, keepdims=True)
        np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-5)

    def test_batchnorm_running_stats(self, tmp_path, rng):
        gamma = rng.rand(4).astype("float32") + 0.5
        beta = rng.randn(4).astype("float32")
        mean = rng.randn(4).astype("float32")
        var = rng.rand(4).astype("float32") + 0.5
        cfg = seq_config([
            {"class_name": "Dense",
             "config": {"name": "d", "output_dim": 4, "activation": "linear",
                        "batch_input_shape": [None, 4]}},
            {"class_name": "BatchNormalization",
             "config": {"name": "bn", "epsilon": 1e-5}},
            {"class_name": "Dense",
             "config": {"name": "o", "output_dim": 2,
                        "activation": "softmax"}},
        ])
        W = np.eye(4, dtype="float32")
        path = str(tmp_path / "bn.h5")
        write_keras_h5(path, cfg, {
            "d": [("d_W", W), ("d_b", np.zeros(4))],
            "bn": [("bn_gamma", gamma), ("bn_beta", beta),
                   ("bn_running_mean", mean), ("bn_running_std", var)],
            "o": [("o_W", rng.randn(4, 2)), ("o_b", np.zeros(2))],
        }, TRAIN_CFG)
        net = import_keras_sequential_model_and_weights(path)
        lk = net.layer_keys[1]
        np.testing.assert_allclose(np.asarray(net.params_tree[lk]["gamma"]), gamma)
        np.testing.assert_allclose(np.asarray(net.state[lk]["mean"]), mean)
        np.testing.assert_allclose(np.asarray(net.state[lk]["var"]), var)
        # eval mode uses the imported running stats
        x = rng.randn(5, 4).astype("float32")
        acts = net.feed_forward(x)
        expect_bn = gamma * (x - mean) / np.sqrt(var + 1e-5) + beta
        np.testing.assert_allclose(np.asarray(acts[1]), expect_bn,
                                   rtol=1e-4, atol=1e-5)


class TestSequentialLSTM:
    def test_keras1_twelve_array_roundtrip(self, tmp_path, rng):
        """Keras-1 W_i,U_i,b_i,W_c,... arrays land in the framework's i,f,o,g
        packing: importing weights exported from one of our LSTM nets must
        reproduce its params and outputs exactly."""
        from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import LSTM, RnnOutputLayer

        f_in, u, c = 3, 4, 2
        ref = MultiLayerNetwork(
            (NeuralNetConfiguration.builder().seed(11).weight_init("xavier")
             .list()
             .layer(LSTM(n_out=u, activation="tanh",
                         gate_activation="sigmoid"))
             .layer(RnnOutputLayer(n_out=c, activation="softmax",
                                   loss_function="mcxent"))
             .set_input_type(InputType.recurrent(f_in))
             .build())
        ).init()
        p = ref.params_tree[ref.layer_keys[0]]
        W = np.asarray(p["W"])  # [f_in, 4u] i,f,o,g
        RW = np.asarray(p["RW"])
        b = np.asarray(p["b"])
        sl = [slice(0, u), slice(u, 2 * u), slice(2 * u, 3 * u),
              slice(3 * u, 4 * u)]
        i, f_, o, g = range(4)
        karrs = [
            ("W_i", W[:, sl[i]]), ("U_i", RW[:, sl[i]]), ("b_i", b[sl[i]]),
            ("W_c", W[:, sl[g]]), ("U_c", RW[:, sl[g]]), ("b_c", b[sl[g]]),
            ("W_f", W[:, sl[f_]]), ("U_f", RW[:, sl[f_]]), ("b_f", b[sl[f_]]),
            ("W_o", W[:, sl[o]]), ("U_o", RW[:, sl[o]]), ("b_o", b[sl[o]]),
        ]
        op = ref.params_tree[ref.layer_keys[1]]
        cfg = seq_config([
            {"class_name": "LSTM",
             "config": {"name": "lstm", "output_dim": u, "activation": "tanh",
                        "inner_activation": "sigmoid",
                        "return_sequences": True,
                        "batch_input_shape": [None, 5, f_in]}},
            {"class_name": "Dense",
             "config": {"name": "out", "output_dim": c,
                        "activation": "softmax"}},
        ])
        path = str(tmp_path / "lstm.h5")
        write_keras_h5(path, cfg, {
            "lstm": [(n, a) for n, a in karrs],
            "out": [("out_W", np.asarray(op["W"])),
                    ("out_b", np.asarray(op["b"]))],
        }, TRAIN_CFG)
        net = import_keras_sequential_model_and_weights(path)
        q = net.params_tree[net.layer_keys[0]]
        np.testing.assert_allclose(np.asarray(q["W"]), W, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(q["RW"]), RW, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(q["b"]), b, rtol=1e-6)

        x = rng.randn(2, 5, f_in).astype("float32")
        np.testing.assert_allclose(net.output(x), ref.output(x),
                                   rtol=1e-5, atol=1e-6)

    def test_return_sequences_false_rejected(self, tmp_path, rng):
        cfg = seq_config([
            {"class_name": "LSTM",
             "config": {"name": "lstm", "output_dim": 3,
                        "return_sequences": False,
                        "batch_input_shape": [None, 5, 2]}},
        ])
        path = str(tmp_path / "bad.h5")
        write_keras_h5(path, cfg, {"lstm": []})
        with pytest.raises(KerasImportException):
            import_keras_sequential_model_and_weights(path)


class TestTrainedModels:
    def test_vgg16_config_builds_and_runs(self, rng):
        """VGG16 zoo topology (reference `TrainedModels.java:16-19`): 13 convs
        in 5 blocks + pools; conv feature extractor runs end to end."""
        from deeplearning4j_tpu import MultiLayerNetwork
        from deeplearning4j_tpu.keras.trained_models import (
            preprocess_imagenet, vgg16_config)

        conf = vgg16_config(n_classes=10, include_top=True, image=224,
                            dtype="float32")
        convs = [l for l in conf.layers if type(l).__name__ == "ConvolutionLayer"]
        assert len(convs) == 13
        assert [l.n_out for l in convs] == [64, 64, 128, 128, 256, 256, 256,
                                            512, 512, 512, 512, 512, 512]

        small = vgg16_config(include_top=False, image=32, dtype="float32")
        net = MultiLayerNetwork(small).init()
        x = preprocess_imagenet(rng.rand(2, 32, 32, 3).astype("float32") * 255)
        out = net.output(x)
        assert out.shape[0] == 2 and np.isfinite(np.asarray(out)).all()


class TestFunctionalModel:
    def test_merge_dag(self, tmp_path, rng):
        """Input -> two Dense branches -> concat Merge -> Dense output."""
        Wa = rng.randn(4, 3).astype("float32")
        Wb = rng.randn(4, 2).astype("float32")
        Wo = rng.randn(5, 2).astype("float32")
        cfg = {
            "class_name": "Model",
            "config": {
                "layers": [
                    {"class_name": "InputLayer", "name": "in",
                     "config": {"name": "in",
                                "batch_input_shape": [None, 4]},
                     "inbound_nodes": []},
                    {"class_name": "Dense", "name": "a",
                     "config": {"name": "a", "output_dim": 3,
                                "activation": "relu"},
                     "inbound_nodes": [[["in", 0, 0]]]},
                    {"class_name": "Dense", "name": "b",
                     "config": {"name": "b", "output_dim": 2,
                                "activation": "tanh"},
                     "inbound_nodes": [[["in", 0, 0]]]},
                    {"class_name": "Merge", "name": "m",
                     "config": {"name": "m", "mode": "concat"},
                     "inbound_nodes": [[["a", 0, 0], ["b", 0, 0]]]},
                    {"class_name": "Dense", "name": "out",
                     "config": {"name": "out", "output_dim": 2,
                                "activation": "softmax"},
                     "inbound_nodes": [[["m", 0, 0]]]},
                ],
                "input_layers": [["in", 0, 0]],
                "output_layers": [["out", 0, 0]],
            },
        }
        path = str(tmp_path / "dag.h5")
        write_keras_h5(path, cfg, {
            "a": [("a_W", Wa), ("a_b", np.zeros(3))],
            "b": [("b_W", Wb), ("b_b", np.zeros(2))],
            "out": [("out_W", Wo), ("out_b", np.zeros(2))],
        }, TRAIN_CFG)
        net = import_keras_model_and_weights(path)
        x = rng.randn(6, 4).astype("float32")
        got = net.output_single(x)
        h = np.concatenate([np.maximum(x @ Wa, 0.0), np.tanh(x @ Wb)], axis=1)
        logits = h @ Wo
        e = np.exp(logits - logits.max(axis=1, keepdims=True))
        np.testing.assert_allclose(got, e / e.sum(axis=1, keepdims=True),
                                   rtol=1e-5, atol=1e-6)


class TestAdviceRegressions:
    """Regression tests for the round-2 advisor findings (ADVICE.md)."""

    def test_functional_channels_last_inferred_from_conv(self, tmp_path, rng):
        """InputLayer configs never carry data_format in real Keras files;
        the ordering must be inferred from the first conv layer. With the
        old 'th' fallback the [None,4,6,3] input parsed as (c=4,h=6,w=3)
        and weight application failed."""
        k = rng.randn(3, 3, 3, 2).astype("float32")  # HWIO, cin=3
        Wo = rng.randn(2 * 2 * 4, 2).astype("float32")
        cfg = {
            "class_name": "Model",
            "config": {
                "layers": [
                    {"class_name": "InputLayer", "name": "in",
                     "config": {"name": "in",
                                "batch_input_shape": [None, 4, 6, 3]},
                     "inbound_nodes": []},
                    {"class_name": "Conv2D", "name": "conv",
                     "config": {"name": "conv", "filters": 2,
                                "kernel_size": [3, 3], "strides": [1, 1],
                                "padding": "valid",
                                "data_format": "channels_last",
                                "activation": "relu"},
                     "inbound_nodes": [[["in", 0, 0]]]},
                    {"class_name": "Flatten", "name": "flat",
                     "config": {"name": "flat"},
                     "inbound_nodes": [[["conv", 0, 0]]]},
                    {"class_name": "Dense", "name": "out",
                     "config": {"name": "out", "units": 2,
                                "activation": "softmax"},
                     "inbound_nodes": [[["flat", 0, 0]]]},
                ],
                "input_layers": [["in", 0, 0]],
                "output_layers": [["out", 0, 0]],
            },
        }
        path = str(tmp_path / "cl.h5")
        write_keras_h5(path, cfg, {
            "conv": [("conv_W", k), ("conv_b", np.zeros(2))],
            "out": [("out_W", Wo), ("out_b", np.zeros(2))],
        }, TRAIN_CFG)
        net = import_keras_model_and_weights(path)
        x = rng.randn(2, 4, 6, 3).astype("float32")
        got = net.output_single(x)
        conv = np.maximum(_conv2d_hwio(x, k, np.zeros(2)), 0.0)
        logits = conv.reshape(2, -1) @ Wo
        e = np.exp(logits - logits.max(axis=1, keepdims=True))
        np.testing.assert_allclose(got, e / e.sum(axis=1, keepdims=True),
                                   rtol=1e-4, atol=1e-5)

    def test_keras2_version_attr_defaults_channels_last(self, tmp_path, rng):
        """No layer records an ordering: the file's keras_version attr
        decides (Keras 2 default = channels_last)."""
        k = rng.randn(3, 3, 3, 2).astype("float32")  # HWIO
        cfg = seq_config([
            {"class_name": "Conv2D",
             "config": {"name": "c", "filters": 2, "kernel_size": [3, 3],
                        "activation": "relu",
                        "batch_input_shape": [None, 4, 6, 3]}},
            {"class_name": "Flatten", "config": {"name": "f"}},
            {"class_name": "Dense",
             "config": {"name": "d", "units": 2, "activation": "softmax"}},
        ])
        path = str(tmp_path / "k2.h5")
        write_keras_h5(path, cfg, {
            "c": [("c_W", k), ("c_b", np.zeros(2))],
            "d": [("d_W", rng.randn(16, 2)), ("d_b", np.zeros(2))],
        }, TRAIN_CFG, keras_version="2.2.4")
        net = import_keras_sequential_model_and_weights(path)
        out = net.output(rng.randn(2, 4, 6, 3).astype("float32"))
        assert out.shape == (2, 2)

    def test_unknown_loss_raises(self, tmp_path, rng):
        cfg = seq_config([
            {"class_name": "Dense",
             "config": {"name": "d", "output_dim": 2, "activation": "softmax",
                        "batch_input_shape": [None, 3]}},
        ])
        path = str(tmp_path / "badloss.h5")
        write_keras_h5(path, cfg,
                       {"d": [("d_W", rng.randn(3, 2)), ("d_b", np.zeros(2))]},
                       {"loss": "my_custom_loss",
                        "optimizer_config": {"config": {"lr": 0.01}}})
        with pytest.raises(KerasImportException, match="loss"):
            import_keras_sequential_model_and_weights(path)

    def test_dict_loss_resolved_per_output(self, tmp_path, rng):
        cfg = seq_config([
            {"class_name": "Dense",
             "config": {"name": "d", "output_dim": 2, "activation": "softmax",
                        "batch_input_shape": [None, 3]}},
        ])
        path = str(tmp_path / "dictloss.h5")
        write_keras_h5(path, cfg,
                       {"d": [("d_W", rng.randn(3, 2)), ("d_b", np.zeros(2))]},
                       {"loss": {"d": "categorical_crossentropy"},
                        "optimizer_config": {"config": {"lr": 0.01}}})
        net = import_keras_sequential_model_and_weights(path)
        assert net.layers[-1].loss_function == "mcxent"

    def test_trailing_dropout_dropped_and_trainable(self, tmp_path, rng):
        """A trailing Dropout previously survived as the last layer, so
        fit() raised 'Last layer is not an output layer'."""
        cfg = seq_config([
            {"class_name": "Dense",
             "config": {"name": "d", "output_dim": 3, "activation": "softmax",
                        "batch_input_shape": [None, 4]}},
            {"class_name": "Dropout", "config": {"name": "drop", "p": 0.3}},
        ])
        path = str(tmp_path / "traildrop.h5")
        write_keras_h5(path, cfg,
                       {"d": [("d_W", rng.randn(4, 3)), ("d_b", np.zeros(3))]},
                       TRAIN_CFG)
        net = import_keras_sequential_model_and_weights(path)
        from deeplearning4j_tpu.datasets.dataset import DataSet
        X = rng.randn(8, 4).astype("float32")
        Y = np.eye(3)[rng.randint(0, 3, 8)].astype("float32")
        s0 = net.score(DataSet(X, Y))
        for _ in range(10):
            net.fit(X, Y)
        assert net.score(DataSet(X, Y)) < s0


_REAL_KERAS_DIR = "/root/reference/deeplearning4j-keras/src/test/resources/theano_mnist"


@pytest.mark.skipif(not os.path.isdir(_REAL_KERAS_DIR),
                    reason="reference Keras fixtures not mounted")
class TestRealKerasGoldenFile:
    """Golden-file test against a model written by REAL Keras 1.1.2 (the
    reference repo's own test resource, produced by actual Keras on the
    Theano backend — not by this repo's h5 writer). Breaks the
    fabricated-fixture circularity: the on-disk attribute layout, weight
    naming, and th-kernel semantics all come from genuine Keras, and the
    expected activations are computed by an independent numpy forward
    straight from the raw h5 arrays (with the Theano 180° kernel flip —
    reference KerasConvolution.java:126-141).

    Reference analog: `KerasModelEndToEndTest.java:42-52`."""

    def _numpy_forward(self, x_nhwc):
        import h5py

        with h5py.File(os.path.join(_REAL_KERAS_DIR, "model.h5"), "r") as f:
            w = f["model_weights"]
            k1 = np.asarray(w["convolution2d_1/convolution2d_1_W"])
            b1 = np.asarray(w["convolution2d_1/convolution2d_1_b"])
            k2 = np.asarray(w["convolution2d_2/convolution2d_2_W"])
            b2 = np.asarray(w["convolution2d_2/convolution2d_2_b"])
            Wd1 = np.asarray(w["dense_1/dense_1_W"])
            bd1 = np.asarray(w["dense_1/dense_1_b"])
            Wd2 = np.asarray(w["dense_2/dense_2_W"])
            bd2 = np.asarray(w["dense_2/dense_2_b"])

        def th_conv(x, k_oihw, b):
            # Theano conv = cross-correlation with the 180°-flipped kernel.
            k = np.transpose(k_oihw[:, :, ::-1, ::-1], (2, 3, 1, 0))  # HWIO
            return _conv2d_hwio(x, k, b)

        h = np.maximum(th_conv(x_nhwc, k1, b1), 0.0)       # 26x26x32
        h = np.maximum(th_conv(h, k2, b2), 0.0)            # 24x24x32
        n, H, W, C = h.shape
        h = h.reshape(n, H // 2, 2, W // 2, 2, C).max(axis=(2, 4))  # 12x12x32
        # Keras th Flatten: [n, c, h, w] -> row-major; our NHWC activations
        # must flatten in the file's channel-first order to use its Dense W.
        flat = np.transpose(h, (0, 3, 1, 2)).reshape(n, -1)
        h = np.maximum(flat @ Wd1 + bd1, 0.0)
        logits = h @ Wd2 + bd2
        e = np.exp(logits - logits.max(axis=1, keepdims=True))
        return e / e.sum(axis=1, keepdims=True)

    def test_real_model_activations_and_accuracy(self):
        import h5py

        net = import_keras_sequential_model_and_weights(
            os.path.join(_REAL_KERAS_DIR, "model.h5"))
        with h5py.File(os.path.join(_REAL_KERAS_DIR, "features", "batch_0.h5"),
                       "r") as f:
            x_nchw = np.asarray(f["data"][:16])
        with h5py.File(os.path.join(_REAL_KERAS_DIR, "labels", "batch_0.h5"),
                       "r") as f:
            y = np.asarray(list(f.values())[0][:16])
        x = np.transpose(x_nchw, (0, 2, 3, 1))  # framework layout NHWC
        got = np.asarray(net.output(x))
        expect = self._numpy_forward(x)
        # The fixture model is UNtrained (near-uniform softmax) — the
        # golden check is exact activation equivalence through the real
        # Keras-written file, not prediction quality.
        np.testing.assert_allclose(got, expect, rtol=1e-3, atol=1e-4)
        assert y.shape[0] == got.shape[0]  # label fixture stays readable


class TestThFlattenDense:
    def test_second_dense_after_flatten_not_permuted(self, tmp_path, rng):
        """Conv -> Pool -> Flatten -> Dense -> Dense (th, no dropout): only
        the FIRST dense's rows are channel-order-permuted; the second must
        import verbatim (regression: the preprocessor walk used to hand the
        first dense's preprocessor to the second and crash on reshape)."""
        k_th = rng.randn(2, 1, 3, 3).astype("float32")
        bc = np.zeros(2, "float32")
        Wd1 = rng.randn(2 * 3 * 3, 5).astype("float32")
        Wd2 = rng.randn(5, 3).astype("float32")
        cfg = seq_config([
            {"class_name": "Convolution2D",
             "config": {"name": "conv", "nb_filter": 2, "nb_row": 3,
                        "nb_col": 3, "subsample": [1, 1],
                        "border_mode": "valid", "dim_ordering": "th",
                        "activation": "relu",
                        "batch_input_shape": [None, 1, 8, 8]}},
            {"class_name": "MaxPooling2D",
             "config": {"name": "pool", "pool_size": [2, 2],
                        "strides": [2, 2], "border_mode": "valid",
                        "dim_ordering": "th"}},
            {"class_name": "Flatten", "config": {"name": "flat"}},
            {"class_name": "Dense",
             "config": {"name": "d1", "output_dim": 5,
                        "activation": "relu"}},
            {"class_name": "Dense",
             "config": {"name": "d2", "output_dim": 3,
                        "activation": "softmax"}},
        ])
        path = str(tmp_path / "two_dense.h5")
        write_keras_h5(path, cfg, {
            "conv": [("conv_W", k_th), ("conv_b", bc)],
            "d1": [("d1_W", Wd1), ("d1_b", np.zeros(5))],
            "d2": [("d2_W", Wd2), ("d2_b", np.zeros(3))],
        }, TRAIN_CFG)
        net = import_keras_sequential_model_and_weights(path)

        x = rng.randn(2, 8, 8, 1).astype("float32")
        k = np.transpose(k_th[:, :, ::-1, ::-1], (2, 3, 1, 0))
        conv = np.maximum(_conv2d_hwio(x, k, bc), 0.0)          # 6x6x2
        pool = conv.reshape(2, 3, 2, 3, 2, 2).max(axis=(2, 4))  # 3x3x2
        flat_th = np.transpose(pool, (0, 3, 1, 2)).reshape(2, -1)
        h = np.maximum(flat_th @ Wd1, 0.0)
        logits = h @ Wd2
        e = np.exp(logits - logits.max(axis=1, keepdims=True))
        np.testing.assert_allclose(net.output(x),
                                   e / e.sum(axis=1, keepdims=True),
                                   rtol=1e-4, atol=1e-5)
