"""DtypePolicy: bf16/f16 mixed-precision training + int8 PTQ serving.

Acceptance coverage for the dtype-policy PR:

- the DEFAULT policy is bit-identical to the pre-policy engines — an
  explicit "float32" policy and an unset one produce the same trained
  trees, opt_state grows no reserved keys, and conf JSON / AOT compile
  fingerprints are unchanged;
- mixed_bfloat16 and pure-bfloat16 training are float-close to f32 (the
  latter via f32 master copies at `opt_state["_master"]`);
- dynamic loss scaling (f16): a non-finite-grad step is SKIPPED (params
  bitwise unchanged) and the scale halves; consecutive finite steps grow
  it back — all carried on-device, so the fused superstep scan stays
  bit-identical to the per-batch loop under the same policy;
- the `transfer_dtype` staging knob halves H2D bytes (counter-verified);
- checkpoints round-trip the policy; a low-precision checkpoint restored
  onto a default-policy net is a clear error, not silent corruption;
- int8 post-training quantization: quantized nets/checkpoints predict
  within tolerance, shrink HBM below 0.55x, serve over HTTP, and report
  dtype via `/v1/models` + `dl4j_serving_model_dtype`;
- tpulint JX009 flags hardcoded compute dtypes in layer forward paths.
"""

import json
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import ml_dtypes

from deeplearning4j_tpu import (MultiLayerNetwork, NeuralNetConfiguration,
                                observability as obs)
from deeplearning4j_tpu.checkpoint import (
    CheckpointError,
    quantize_checkpoint,
    quantize_net,
    restore_checkpoint,
    save_checkpoint,
)
from deeplearning4j_tpu.checkpoint import quantize as quantize_mod
from deeplearning4j_tpu.checkpoint import store as ckpt_store
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (
    SuperbatchIterator,
    stage_to_device,
    transfer_cast,
)
from deeplearning4j_tpu.nn.conf.dtype_policy import DtypePolicy, resolve_policy
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer

from conftest import make_classification_data

N_IN, N_OUT = 4, 3
BF16 = np.dtype(ml_dtypes.bfloat16)


def mlp_conf(policy=None, updater="adam", superstep_k=0, seed=7):
    b = (NeuralNetConfiguration.builder()
         .seed(seed).learning_rate(0.05).updater(updater)
         .weight_init("xavier").superstep_k(superstep_k))
    if policy is not None:
        b = b.dtype_policy(policy)
    return (b.list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=N_OUT, activation="softmax",
                               loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(N_IN))
            .build())


def make_batches(rng, n_batches=4, batch=6):
    out = []
    for _ in range(n_batches):
        X, Y = make_classification_data(rng, n=batch, n_features=N_IN,
                                        n_classes=N_OUT, dtype="float32")
        out.append(DataSet(X, Y))
    return out


def train(policy=None, batches=None, rng=None, **kw):
    net = MultiLayerNetwork(mlp_conf(policy=policy, **kw)).init()
    for ds in batches if batches is not None else make_batches(rng):
        net.fit(ds)
    return net


def assert_trees_identical(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def layer_param_keys(opt_state):
    return {k for k in opt_state if not k.startswith("_")}


def counter_total(name, **match):
    fam = obs.metrics.get_family(name)
    if fam is None:
        return 0.0
    total = 0.0
    for child in fam.children():
        if all(child.labels.get(k) == v for k, v in match.items()):
            total += child.get()
    return total


# ---------------------------------------------------------------- policy


class TestPolicyObject:
    def test_presets(self):
        p = DtypePolicy.of("mixed_bfloat16")
        assert (p.resolved_param_dtype, p.resolved_compute_dtype,
                p.resolved_output_dtype) == ("float32", "bfloat16", "float32")
        assert not p.uses_loss_scaling and not p.low_precision_params
        p = DtypePolicy.of("mixed_float16")
        assert p.uses_loss_scaling and not p.low_precision_params
        p = DtypePolicy.of("bfloat16")
        assert p.low_precision_params and not p.uses_loss_scaling
        assert DtypePolicy.of("f16").uses_loss_scaling

    def test_of_coercions_and_roundtrip(self):
        assert DtypePolicy.of(None).is_default
        d = {"name": "mixed_bfloat16", "transfer_dtype": "bfloat16"}
        p = DtypePolicy.of(d)
        assert p.transfer_dtype == "bfloat16"
        assert DtypePolicy.of(p.to_dict()) == p
        with pytest.raises(ValueError, match="unknown dtype policy"):
            DtypePolicy.of("int7")
        with pytest.raises(TypeError):
            DtypePolicy.of(42)

    def test_legacy_dtype_string_maps_to_preset(self):
        conf = mlp_conf()
        conf.global_conf.dtype = "bfloat16"
        assert resolve_policy(conf.global_conf).name == "mixed_bfloat16"
        conf.global_conf.dtype = "float64"
        assert resolve_policy(conf.global_conf).name == "float64"
        conf.global_conf.dtype_policy = "bfloat16"  # explicit policy wins
        assert resolve_policy(conf.global_conf).name == "bfloat16"


# ------------------------------------------------------- default identity


class TestDefaultBitIdentity:
    def test_explicit_f32_policy_is_bitwise_default(self, rng):
        batches = make_batches(rng)
        a = train(policy=None, batches=batches)
        b = train(policy="float32", batches=batches)
        assert_trees_identical(a.params_tree, b.params_tree)
        assert_trees_identical(a.opt_state, b.opt_state)

    def test_default_opt_state_has_no_reserved_keys(self, rng):
        net = train(policy=None, rng=rng)
        assert "_master" not in net.opt_state
        assert "_ls" not in net.opt_state

    def test_default_conf_json_omits_policy(self):
        assert "dtype_policy" not in mlp_conf().to_json()
        assert "dtype_policy" in mlp_conf(policy="mixed_bfloat16").to_json()


# -------------------------------------------------------- mixed precision


class TestMixedPrecisionTraining:
    def test_mixed_bfloat16_float_close_to_f32(self, rng):
        batches = make_batches(rng)
        f32 = train(policy=None, batches=batches)
        bf = train(policy="mixed_bfloat16", batches=batches)
        # Params stay f32 masters-by-construction (no _master needed).
        assert "_master" not in bf.opt_state
        for lp in bf.params_tree.values():
            for a in lp.values():
                assert a.dtype == jnp.float32
        for x, y in zip(jax.tree_util.tree_leaves(f32.params_tree),
                        jax.tree_util.tree_leaves(bf.params_tree)):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       atol=0.1, rtol=0.1)
        X = np.asarray(batches[0].features)
        assert np.asarray(bf.output(X)).dtype == np.float32

    def test_pure_bfloat16_keeps_f32_masters(self, rng):
        batches = make_batches(rng)
        f32 = train(policy=None, batches=batches)
        bf = train(policy="bfloat16", batches=batches)
        assert "_master" in bf.opt_state
        for lp in bf.params_tree.values():
            for a in lp.values():
                assert a.dtype == BF16
        for lp in bf.opt_state["_master"].values():
            for a in lp.values():
                assert a.dtype == jnp.float32
        for x, y in zip(jax.tree_util.tree_leaves(f32.params_tree),
                        jax.tree_util.tree_leaves(bf.opt_state["_master"])):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       atol=0.1, rtol=0.1)
        X = np.asarray(batches[0].features)
        assert np.asarray(bf.output(X)).dtype == BF16


# ----------------------------------------------------------- loss scaling


class TestDynamicLossScaling:
    def test_scale_initialized_on_device(self, rng):
        net = train(policy="mixed_float16", batches=make_batches(rng, 1))
        scale, good = net.opt_state["_ls"]
        assert isinstance(scale, jax.Array)
        assert float(scale) in (2.0 ** 15, 2.0 ** 14)

    def test_nonfinite_step_skipped_and_scale_halved(self, rng):
        net = train(policy="mixed_float16", batches=make_batches(rng, 2))
        before = jax.tree_util.tree_map(np.asarray, net.params_tree)
        scale0 = float(net.opt_state["_ls"][0])
        X, Y = make_classification_data(rng, n=6, n_features=N_IN,
                                        n_classes=N_OUT, dtype="float32")
        X[0, 0] = np.inf
        net.fit(DataSet(X, Y))
        assert_trees_identical(before, net.params_tree)
        assert float(net.opt_state["_ls"][0]) == scale0 * 0.5

    def test_scale_grows_after_finite_interval(self, rng):
        pol = {"name": "mixed_float16", "initial_loss_scale": 8.0,
               "loss_scale_growth_interval": 2}
        net = train(policy=pol, batches=make_batches(rng, 4))
        # 4 finite steps with interval 2 -> two doublings: 8 -> 32.
        assert float(net.opt_state["_ls"][0]) == 32.0

    def test_superstep_scan_bit_identical_under_scaling(self, rng):
        batches = make_batches(rng, n_batches=4)
        pol = {"name": "mixed_float16", "initial_loss_scale": 8.0,
               "loss_scale_growth_interval": 2}
        seq = train(policy=pol, batches=batches, superstep_k=0)
        fused = MultiLayerNetwork(
            mlp_conf(policy=pol, superstep_k=4)).init()
        fused.fit(batches)
        assert_trees_identical(seq.params_tree, fused.params_tree)
        assert_trees_identical(seq.opt_state, fused.opt_state)

    def test_solver_and_pretrain_reject_scaling_policies(self, rng):
        net = train(policy="mixed_float16", batches=make_batches(rng, 1))
        with pytest.raises(ValueError, match="dtype policy"):
            net._check_sgd_only_policy("solver optimizers")
        net = train(policy="bfloat16", batches=make_batches(rng, 1))
        with pytest.raises(ValueError, match="dtype policy"):
            net._check_sgd_only_policy("layerwise pretraining")


# ------------------------------------------------------- transfer staging


class TestTransferStaging:
    def test_transfer_cast_halves_host_bytes(self, rng):
        ds = make_batches(rng, 1, batch=8)[0]
        cast = transfer_cast(ds, "bfloat16")
        assert cast.features.dtype == BF16
        assert cast.features.nbytes * 2 == ds.features.nbytes
        assert cast.labels.dtype == BF16
        # None / ints / masks pass through untouched.
        assert transfer_cast(ds, None) is ds
        ids = DataSet(np.arange(12, dtype=np.int32).reshape(3, 4),
                      ds.labels[:3], labels_mask=np.ones(3, np.float32))
        cast = transfer_cast(ids, "bfloat16")
        assert cast.features.dtype == np.int32
        assert cast.labels_mask.dtype == np.float32

    def test_stage_and_superbatch_ship_reduced(self, rng):
        ds = make_batches(rng, 1, batch=8)[0]
        staged = stage_to_device(ds, transfer_dtype="bfloat16")
        assert staged.features.dtype == BF16
        blocks = list(SuperbatchIterator(make_batches(rng, 4), k=4,
                                         stage=False,
                                         transfer_dtype="bfloat16"))
        assert blocks[0].features.dtype == BF16

    def test_h2d_counter_confirms_halved_transfer(self, rng):
        batches = make_batches(rng, 2, batch=16)

        def shipped(policy):
            net = MultiLayerNetwork(mlp_conf(policy=policy)).init()
            before = counter_total("dl4j_host_to_device_bytes_total",
                                   engine="mln")
            for ds in batches:
                net.fit(ds)
            return counter_total("dl4j_host_to_device_bytes_total",
                                 engine="mln") - before

        full = shipped(None)
        half = shipped({"name": "mixed_bfloat16",
                        "transfer_dtype": "bfloat16"})
        assert full > 0
        assert half == pytest.approx(full / 2)


# ------------------------------------------------------------ checkpoints


class TestCheckpointPolicy:
    def test_default_meta_has_no_policy_and_roundtrips(self, rng, tmp_path):
        net = train(policy=None, rng=rng)
        path = save_checkpoint(net, str(tmp_path / "ckpt"))
        assert "dtype_policy" not in ckpt_store.read_meta(path)
        back = restore_checkpoint(path)
        assert_trees_identical(net.params_tree, back.params_tree)

    def test_policy_roundtrips_through_meta(self, rng, tmp_path):
        net = train(policy="bfloat16", rng=rng)
        path = save_checkpoint(net, str(tmp_path / "ckpt"))
        meta = ckpt_store.read_meta(path)
        assert DtypePolicy.of(meta["dtype_policy"]).name == "bfloat16"
        back = restore_checkpoint(path)
        assert back.dtype_policy.name == "bfloat16"
        for lp in back.params_tree.values():
            for a in lp.values():
                assert a.dtype == BF16
        assert "_master" in back.opt_state

    def test_low_precision_onto_default_net_is_clear_error(self, rng,
                                                           tmp_path):
        path = save_checkpoint(train(policy="bfloat16", rng=rng),
                               str(tmp_path / "ckpt"))
        target = MultiLayerNetwork(mlp_conf()).init()
        with pytest.raises(CheckpointError, match="dtype policy"):
            restore_checkpoint(path, net=target)

    def test_quantized_checkpoint_roundtrip_and_cli(self, rng, tmp_path):
        net = train(policy=None, rng=rng)
        src = save_checkpoint(net, str(tmp_path / "f32"))
        assert quantize_mod.main([src, str(tmp_path / "int8")]) == 0
        meta = ckpt_store.read_meta(str(tmp_path / "int8"))
        assert meta["quantization"]["scheme"] == "int8_per_channel_symmetric"
        qnet = restore_checkpoint(str(tmp_path / "int8"))
        W = qnet.params_tree["layer_0"]["W"]
        assert W.dtype == jnp.int8
        assert "W__scale" in qnet.params_tree["layer_0"]
        X = np.random.RandomState(0).rand(8, N_IN).astype(np.float32)
        np.testing.assert_allclose(np.asarray(qnet.output(X)),
                                   np.asarray(net.output(X)), atol=0.05)
        # Re-quantizing an already-quantized checkpoint is refused.
        with pytest.raises(CheckpointError, match="already"):
            quantize_checkpoint(str(tmp_path / "int8"),
                                str(tmp_path / "int8b"))


# ------------------------------------------------------- AOT fingerprints


class TestAOTFingerprint:
    def _fp(self, net):
        from deeplearning4j_tpu.compilation import store as store_mod
        from deeplearning4j_tpu.compilation import warmup as warmup_mod

        r = np.random.RandomState(0)
        ds = DataSet(r.rand(8, N_IN).astype("float32"),
                     np.eye(N_OUT, dtype="float32")[r.randint(0, N_OUT, 8)])
        args = warmup_mod._mln_args(net, ds, "train_step")
        return store_mod.fingerprint(
            store_mod.build_fingerprint_doc(net, "train_step", {}, args))

    def test_policy_only_change_invalidates(self):
        default = MultiLayerNetwork(mlp_conf()).init()
        policied = MultiLayerNetwork(
            mlp_conf(policy="mixed_bfloat16")).init()
        rebuilt = MultiLayerNetwork(mlp_conf()).init()
        assert self._fp(default) == self._fp(rebuilt)
        assert self._fp(default) != self._fp(policied)


# ----------------------------------------------------------------- JX009


class TestJX009:
    LAYER_PATH = "deeplearning4j_tpu/nn/layers/fake.py"

    def _findings(self, src, path=None):
        from deeplearning4j_tpu.analysis.linter import lint_source

        return lint_source(src, path or self.LAYER_PATH, rules=["JX009"])

    def test_flags_hardcoded_compute_dtype(self):
        src = ("import jax.numpy as jnp\n"
               "def forward(x, w):\n"
               "    x = x.astype(jnp.float32)\n"
               "    return jnp.dot(x, w, preferred_element_type=None)"
               ".astype(jnp.float16)\n")
        assert len(self._findings(src)) == 2

    def test_promote_types_widening_is_exempt(self):
        src = ("import jax.numpy as jnp\n"
               "def forward(x):\n"
               "    acc = jnp.promote_types(x.dtype, jnp.float32)\n"
               "    return x.astype(acc)\n")
        assert self._findings(src) == []

    def test_only_layer_forward_paths_are_scoped(self):
        src = ("import jax.numpy as jnp\n"
               "def helper(x):\n"
               "    return x.astype(jnp.float32)\n")
        assert self._findings(
            src, path="deeplearning4j_tpu/datasets/iterators.py") == []

    def test_dtype_string_keyword_flagged(self):
        src = ("import jax.numpy as jnp\n"
               "def forward(x):\n"
               "    return jnp.zeros((2, 2), dtype='float32') + x\n")
        assert len(self._findings(src)) == 1


# ---------------------------------------------------------- int8 serving


class TestInt8Serving:
    def test_quantized_model_serves_over_http(self, rng, tmp_path):
        from deeplearning4j_tpu.serving import InferenceServer
        from deeplearning4j_tpu.serving.host import estimate_hbm_bytes

        net = train(policy=None, rng=rng)
        f32_bytes = estimate_hbm_bytes(net)
        f32_out = np.asarray(net.output(
            np.random.RandomState(1).rand(4, N_IN).astype(np.float32)))

        path = save_checkpoint(net, str(tmp_path / "f32"))
        quantize_checkpoint(path, str(tmp_path / "int8"))
        qnet = restore_checkpoint(str(tmp_path / "int8"))
        assert estimate_hbm_bytes(qnet) <= 0.55 * f32_bytes

        server = InferenceServer(qnet, port=0, default_model="q",
                                 max_batch_size=8, max_delay_ms=1.0).start()
        try:
            x = np.random.RandomState(1).rand(4, N_IN).astype(np.float32)
            got = np.asarray(server.predict(x))
            np.testing.assert_allclose(got, f32_out, atol=0.05)
            with urllib.request.urlopen(server.url + "/v1/models",
                                        timeout=10) as r:
                rows = {m["name"]: m for m in json.loads(r.read())["models"]}
            assert rows["q"]["dtype"] == "int8"
            with urllib.request.urlopen(server.url + "/metrics",
                                        timeout=10) as r:
                scrape = r.read().decode()
            assert ('dl4j_serving_model_dtype{dtype="int8",model="q"} 1'
                    in scrape
                    or 'dl4j_serving_model_dtype{model="q",dtype="int8"} 1'
                    in scrape)
            # The sharding info gauge rides the same one-scrape surface:
            # an unsharded host reports layout 'none' (PR 20 exports
            # 'model:<n>-way' for tensor-parallel models).
            assert rows["q"]["sharding"] == "none"
            assert ('dl4j_serving_model_sharding{model="q",sharding="none"}'
                    ' 1' in scrape
                    or 'dl4j_serving_model_sharding{sharding="none",'
                    'model="q"} 1' in scrape)
        finally:
            server.stop()

    def test_quantize_net_in_place(self, rng):
        net = train(policy=None, rng=rng)
        X = np.random.RandomState(2).rand(6, N_IN).astype(np.float32)
        want = np.asarray(net.output(X))
        quantize_net(net)
        assert net.params_tree["layer_0"]["W"].dtype == jnp.int8
        np.testing.assert_allclose(np.asarray(net.output(X)), want,
                                   atol=0.05)
