"""Graph API + DeepWalk tests.

Reference analogs: `deeplearning4j-graph/src/test/` — `TestGraph.java`
(adjacency/degree/edge handling), `TestGraphLoading.java` (edge-list files),
`DeepWalkGradientCheck.java` / `TestDeepWalk.java` (fit on a small graph,
similarity sanity, save/load round-trip).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.graph import DeepWalk, Graph, GraphVectors, NoEdgeHandling
from deeplearning4j_tpu.graph.api import NoEdgesException
from deeplearning4j_tpu.graph.data import load_undirected_graph, load_weighted_graph
from deeplearning4j_tpu.graph.deepwalk import huffman_codes
from deeplearning4j_tpu.graph.iterators import RandomWalkIterator, random_walks


def _two_communities(rng, size=10, p=0.6):
    g = Graph(2 * size)
    for base in (0, size):
        for i in range(base, base + size):
            for j in range(i + 1, base + size):
                if rng.rand() < p:
                    g.add_edge(i, j)
    g.add_edge(size - 1, size)  # bridge
    return g


class TestGraphApi:
    def test_undirected_edge_degree(self):
        g = Graph(4)
        g.add_edge(0, 1)
        g.add_edge(1, 2, directed=True)
        assert g.get_vertex_degree(0) == 1
        assert g.get_vertex_degree(1) == 2  # undirected back-edge + out-edge
        assert g.get_vertex_degree(2) == 0  # directed edge adds no out-edge
        assert list(g.get_connected_vertex_indices(1)) == [0, 2]
        assert g.num_edges() == 2

    def test_loaders(self, tmp_path):
        p = tmp_path / "edges.csv"
        p.write_text("# comment\n0,1\n1,2\n")
        g = load_undirected_graph(str(p), 3)
        assert g.num_edges() == 2
        pw = tmp_path / "weighted.csv"
        pw.write_text("0,1,2.5\n")
        gw = load_weighted_graph(str(pw), 2)
        _, cumw, _ = gw.neighbor_table()
        assert cumw[0, 0] == pytest.approx(2.5)


class TestRandomWalks:
    def test_shapes_and_connectivity(self, rng):
        g = _two_communities(np.random.RandomState(0))
        walks = random_walks(g, 8, rng=np.random.RandomState(1))
        assert walks.shape == (20, 9)
        assert (walks[:, 0] == np.arange(20)).all()
        # Every consecutive pair is an actual edge.
        for row in walks:
            for a, b in zip(row[:-1], row[1:]):
                assert b in g.get_connected_vertex_indices(int(a))

    def test_self_loop_on_disconnected(self):
        g = Graph(3)
        g.add_edge(0, 1)
        walks = random_walks(g, 5, rng=np.random.RandomState(0))
        assert (walks[2] == 2).all()  # isolated vertex 2 stays put

    def test_exception_on_disconnected(self):
        g = Graph(3)
        g.add_edge(0, 1)
        with pytest.raises(NoEdgesException):
            random_walks(g, 5,
                         no_edge_handling=NoEdgeHandling.EXCEPTION_ON_DISCONNECTED)

    def test_iterator_facade(self):
        g = Graph(4)
        for i in range(3):
            g.add_edge(i, i + 1)
        it = RandomWalkIterator(g, 6, seed=7)
        walks = list(it)
        assert len(walks) == 4
        assert it.walk_length() == 6
        it.reset()
        assert it.has_next()
        np.testing.assert_array_equal(it.next(), walks[0])  # deterministic


class TestHuffman:
    def test_codes_prefix_free_and_degree_ordered(self):
        codes, points, n_inner = huffman_codes(np.array([10, 1, 1, 1, 5]))
        assert len(codes) == 5 and n_inner == 4
        assert len(codes[0]) == min(len(c) for c in codes)
        # Prefix-free: no code is a prefix of another.
        tuples = [tuple(c) for c in codes]
        for i, a in enumerate(tuples):
            for j, b in enumerate(tuples):
                if i != j:
                    assert a != b[: len(a)]


class TestDeepWalk:
    def test_community_separation(self, rng):
        g = _two_communities(np.random.RandomState(0))
        dw = DeepWalk(vector_size=16, window_size=3, learning_rate=0.05,
                      epochs=30, seed=3, batch_size=512)
        dw.fit(g, walk_length=20)
        within = np.mean([dw.similarity(i, j)
                          for i in range(10) for j in range(i + 1, 10)])
        across = np.mean([dw.similarity(i, j)
                          for i in range(10) for j in range(10, 20)])
        assert within > across + 0.3, (within, across)
        assert all(n < 10 for n in dw.vertices_nearest(0, 3))

    def test_save_load_roundtrip(self, rng, tmp_path):
        g = _two_communities(np.random.RandomState(1), size=5)
        dw = DeepWalk(vector_size=8, epochs=2, seed=1).fit(g, walk_length=8)
        path = str(tmp_path / "vecs.txt")
        dw.save(path)
        gv = GraphVectors.load(path)
        np.testing.assert_allclose(gv.syn0, dw.syn0.astype(np.float32),
                                   atol=1e-6)
        assert gv.num_vertices() == 10

    def test_initialize_from_degrees(self):
        dw = DeepWalk(vector_size=4).initialize(np.array([3, 2, 1, 1]))
        assert dw._syn0.shape == (4, 4)

