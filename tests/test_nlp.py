"""NLP embedding tests (Word2Vec / GloVe / ParagraphVectors).

Reference analogs: `deeplearning4j-nlp` test suite — `Word2VecTests.java`,
`models/glove/GloveTest.java` (fit on a small corpus, check similarity /
nearest words), vocab + Huffman construction tests. Small synthetic
two-topic corpora keep runtime test-suite friendly.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp.glove import CoOccurrences, Glove
from deeplearning4j_tpu.nlp.vocab import VocabConstructor, build_huffman
from deeplearning4j_tpu.nlp.word2vec import Word2Vec

ANIMALS = ["cat", "dog", "bird", "fish", "horse"]
VEHICLES = ["car", "truck", "bus", "train", "plane"]


def _cluster_corpus(rng, n=300, length=6):
    sents = []
    for _ in range(n):
        group = ANIMALS if rng.rand() < 0.5 else VEHICLES
        sents.append(" ".join(rng.choice(group, length)))
    return sents


class TestVocab:
    def test_min_frequency_and_order(self):
        cache = VocabConstructor(min_word_frequency=2).build(
            [["a", "a", "a", "b", "b", "c"]])
        assert cache.words() == ["a", "b"]  # c dropped, sorted by frequency
        assert cache.index_of("a") == 0

    def test_huffman_prefix_free(self):
        cache = VocabConstructor().build(
            [["w%d" % i] * (i + 1) for i in range(8)])
        build_huffman(cache)
        codes = {tuple(w.codes) for w in cache._by_index}
        assert len(codes) == 8  # all distinct
        # Most frequent word gets the shortest code.
        lengths = [len(w.codes) for w in cache._by_index]
        assert lengths[0] == min(lengths)


class TestCoOccurrences:
    def test_distance_weighting(self):
        rows, cols, vals = CoOccurrences(window_size=2).count(
            [np.array([0, 1, 2], np.int32)], 3)
        got = {(int(r), int(c)): float(v) for r, c, v in zip(rows, cols, vals)}
        # (0,1) and (1,2) adjacent -> 1.0; (0,2) at distance 2 -> 0.5
        assert got[(0, 1)] == pytest.approx(1.0)
        assert got[(1, 2)] == pytest.approx(1.0)
        assert got[(0, 2)] == pytest.approx(0.5)

    def test_window_cutoff(self):
        rows, cols, vals = CoOccurrences(window_size=1).count(
            [np.array([0, 1, 2], np.int32)], 3)
        got = {(int(r), int(c)) for r, c in zip(rows, cols)}
        assert (0, 2) not in got


class TestGlove:
    def test_clusters_and_error_decreases(self, rng):
        sents = _cluster_corpus(rng)
        g = Glove(sents, layer_size=24, epochs=20, window_size=5, seed=1,
                  batch_size=64).fit()
        assert g.error_per_epoch[-1] < g.error_per_epoch[0] * 0.1
        assert g.similarity("cat", "dog") > 0.5
        assert g.similarity("cat", "car") < 0.5
        assert set(g.words_nearest("cat", 4)) == set(ANIMALS) - {"cat"}

    def test_empty_corpus_raises(self):
        with pytest.raises(ValueError):
            Glove([""], epochs=1).fit()


class TestWord2Vec:
    @pytest.mark.parametrize("kwargs", [
        dict(negative=0, cbow=False),   # skip-gram hierarchical softmax
        dict(negative=5, cbow=False),   # skip-gram negative sampling
        dict(negative=0, cbow=True),    # CBOW hierarchical softmax
    ])
    def test_clusters(self, rng, kwargs):
        sents = _cluster_corpus(rng, n=250)
        w = Word2Vec(sents, layer_size=24, epochs=3, window_size=4, seed=1,
                     learning_rate=0.05, batch_size=256, **kwargs).fit()
        within = w.similarity("cat", "dog")
        across = w.similarity("cat", "car")
        assert within > across, (kwargs, within, across)
