"""NLP embedding tests (Word2Vec / GloVe / ParagraphVectors).

Reference analogs: `deeplearning4j-nlp` test suite — `Word2VecTests.java`,
`models/glove/GloveTest.java` (fit on a small corpus, check similarity /
nearest words), vocab + Huffman construction tests. Small synthetic
two-topic corpora keep runtime test-suite friendly.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.nlp import serializer
from deeplearning4j_tpu.nlp.glove import CoOccurrences, Glove
from deeplearning4j_tpu.nlp.vocab import VocabConstructor, build_huffman
from deeplearning4j_tpu.nlp.word2vec import Word2Vec

ANIMALS = ["cat", "dog", "bird", "fish", "horse"]
VEHICLES = ["car", "truck", "bus", "train", "plane"]


def _cluster_corpus(rng, n=300, length=6):
    sents = []
    for _ in range(n):
        group = ANIMALS if rng.rand() < 0.5 else VEHICLES
        sents.append(" ".join(rng.choice(group, length)))
    return sents


class TestVocab:
    def test_min_frequency_and_order(self):
        cache = VocabConstructor(min_word_frequency=2).build(
            [["a", "a", "a", "b", "b", "c"]])
        assert cache.words() == ["a", "b"]  # c dropped, sorted by frequency
        assert cache.index_of("a") == 0

    def test_huffman_prefix_free(self):
        cache = VocabConstructor().build(
            [["w%d" % i] * (i + 1) for i in range(8)])
        build_huffman(cache)
        codes = {tuple(w.codes) for w in cache._by_index}
        assert len(codes) == 8  # all distinct
        # Most frequent word gets the shortest code.
        lengths = [len(w.codes) for w in cache._by_index]
        assert lengths[0] == min(lengths)


class TestCoOccurrences:
    def test_distance_weighting(self):
        rows, cols, vals = CoOccurrences(window_size=2).count(
            [np.array([0, 1, 2], np.int32)])
        got = {(int(r), int(c)): float(v) for r, c, v in zip(rows, cols, vals)}
        # (0,1) and (1,2) adjacent -> 1.0; (0,2) at distance 2 -> 0.5
        assert got[(0, 1)] == pytest.approx(1.0)
        assert got[(1, 2)] == pytest.approx(1.0)
        assert got[(0, 2)] == pytest.approx(0.5)

    def test_window_cutoff(self):
        rows, cols, vals = CoOccurrences(window_size=1).count(
            [np.array([0, 1, 2], np.int32)])
        got = {(int(r), int(c)) for r, c in zip(rows, cols)}
        assert (0, 2) not in got


class TestGlove:
    def test_clusters_and_error_decreases(self, rng):
        sents = _cluster_corpus(rng)
        g = Glove(sents, layer_size=24, epochs=20, window_size=5, seed=1,
                  batch_size=64).fit()
        assert g.error_per_epoch[-1] < g.error_per_epoch[0] * 0.1
        assert g.similarity("cat", "dog") > 0.5
        assert g.similarity("cat", "car") < 0.5
        assert set(g.words_nearest("cat", 4)) == set(ANIMALS) - {"cat"}

    def test_empty_corpus_raises(self):
        with pytest.raises(ValueError):
            Glove([""], epochs=1).fit()


class TestWord2Vec:
    @pytest.mark.parametrize("kwargs", [
        dict(negative=0, cbow=False),   # skip-gram hierarchical softmax
        dict(negative=5, cbow=False),   # skip-gram negative sampling
        dict(negative=0, cbow=True),    # CBOW hierarchical softmax
        dict(negative=5, cbow=True),    # CBOW negative sampling
    ])
    def test_clusters(self, rng, kwargs):
        sents = _cluster_corpus(rng, n=250)
        w = Word2Vec(sents, layer_size=24, epochs=3, window_size=4, seed=1,
                     learning_rate=0.05, batch_size=256, **kwargs).fit()
        within = w.similarity("cat", "dog")
        across = w.similarity("cat", "car")
        assert within > across, (kwargs, within, across)


class TestWordVectorSerializer:
    """Reference analog: `WordVectorSerializerTest.java` — Google
    binary/text round-trips against hand-written fixtures."""

    def _tiny_model(self, rng):
        sents = _cluster_corpus(rng, n=40)
        return Word2Vec(sents, layer_size=8, epochs=1, seed=1,
                        batch_size=64).fit()

    def test_text_roundtrip(self, rng, tmp_path):
        w = self._tiny_model(rng)
        p = str(tmp_path / "vecs.txt")
        serializer.write_word_vectors(w, p)
        back = serializer.load_google_model(p, binary=False)
        assert back.vocab.words() == w.vocab.words()
        np.testing.assert_allclose(back.syn0, np.asarray(w.syn0, np.float32),
                                   rtol=1e-5)

    def test_text_no_header(self, rng, tmp_path):
        w = self._tiny_model(rng)
        p = str(tmp_path / "vecs.txt")
        serializer.write_word_vectors(w, p, header=False)
        back = serializer.load_txt_vectors(p)
        assert back.vocab.words() == w.vocab.words()

    def test_binary_roundtrip(self, rng, tmp_path):
        w = self._tiny_model(rng)
        p = str(tmp_path / "vecs.bin")
        serializer.write_google_binary(w, p)
        back = serializer.load_google_model(p, binary=True)
        assert back.vocab.words() == w.vocab.words()
        np.testing.assert_allclose(back.syn0, np.asarray(w.syn0, np.float32))

    def test_binary_hand_written_fixture(self, tmp_path):
        """Bytes laid out by hand in the Google .bin format — a shared
        write/read misunderstanding cannot pass this."""
        import struct
        p = tmp_path / "fixture.bin"
        vecs = {"hello": [1.0, -2.5, 3.25], "world": [0.5, 0.0, -1.0]}
        blob = b"2 3\n"
        for word, v in vecs.items():
            blob += word.encode() + b" " + struct.pack("<3f", *v) + b"\n"
        p.write_bytes(blob)
        back = serializer.load_google_binary(str(p))
        assert back.vocab.words() == ["hello", "world"]
        np.testing.assert_allclose(back.get_word_vector("hello"),
                                   [1.0, -2.5, 3.25])
        assert back.similarity("hello", "world") == pytest.approx(
            float(np.dot([1.0, -2.5, 3.25], [0.5, 0.0, -1.0])
                  / np.linalg.norm([1.0, -2.5, 3.25])
                  / np.linalg.norm([0.5, 0.0, -1.0])), abs=1e-6)

    def test_full_model_roundtrip(self, rng, tmp_path):
        w = self._tiny_model(rng)
        p = str(tmp_path / "model.zip")
        serializer.write_full_model(w, p)
        back = serializer.load_full_model(p)
        assert back.layer_size == w.layer_size
        assert back.vocab.words() == w.vocab.words()
        np.testing.assert_allclose(np.asarray(back.syn0),
                                   np.asarray(w.syn0, np.float32))
        np.testing.assert_allclose(np.asarray(back.syn1),
                                   np.asarray(w.syn1, np.float32))
        # Huffman codes survive (needed to continue training).
        w0, b0 = w.vocab._by_index[0], back.vocab._by_index[0]
        assert w0.codes == b0.codes and w0.points == b0.points

    def test_full_model_resume_training(self, rng, tmp_path):
        """The point of the full-model format: a loaded model can keep
        training (vocab/weights reused, not rebuilt)."""
        w = self._tiny_model(rng)
        p = str(tmp_path / "model.zip")
        serializer.write_full_model(w, p)
        back = serializer.load_full_model(p)
        with pytest.raises(ValueError, match="sentences"):
            back.fit()
        vocab_before = back.vocab
        syn0_before = np.asarray(back.syn0).copy()
        back.fit(_cluster_corpus(rng, n=20))
        assert back.vocab is vocab_before          # not rebuilt
        assert not np.allclose(syn0_before, np.asarray(back.syn0))


class TestVectorizers:
    """Reference: `BagOfWordsVectorizerTest.java` / `TfidfVectorizerTest.java`
    — counts, tf*log10(N/df) weighting, vectorize() DataSet shape."""

    DOCS = ["the cat sat on the mat",
            "the dog sat on the log",
            "cats and dogs"]

    def test_bag_of_words_counts(self):
        from deeplearning4j_tpu.nlp.vectorizer import BagOfWordsVectorizer

        v = BagOfWordsVectorizer().fit(self.DOCS)
        vec = v.transform("the cat and the cat")
        assert vec[v.vocab.index("cat")] == 2
        assert vec[v.vocab.index("the")] == 2
        assert vec[v.vocab.index("and")] == 1
        assert vec.sum() == 5

    def test_tfidf_weighting(self):
        from deeplearning4j_tpu.nlp.vectorizer import TfidfVectorizer

        v = TfidfVectorizer().fit(self.DOCS)
        vec = v.transform("cat cat dog")
        # tf("cat")=2/3; df("cat")=1 of 3 docs -> idf=log10(3)
        np.testing.assert_allclose(vec[v.vocab.index("cat")],
                                   (2 / 3) * np.log10(3))
        # "the" appears in 2 of 3 docs
        v2 = v.transform("the")
        np.testing.assert_allclose(v2[v.vocab.index("the")],
                                   1.0 * np.log10(3 / 2))

    def test_vectorize_dataset(self):
        from deeplearning4j_tpu.nlp.vectorizer import TfidfVectorizer

        v = TfidfVectorizer(labels=["pets", "other"]).fit(self.DOCS)
        ds = v.vectorize("the cat sat", "pets")
        assert ds.features.shape == (1, len(v.vocab))
        np.testing.assert_array_equal(ds.labels, [[1.0, 0.0]])
        with pytest.raises(ValueError):
            v.vectorize("x", "nope")

    def test_min_word_frequency(self):
        from deeplearning4j_tpu.nlp.vectorizer import BagOfWordsVectorizer

        v = BagOfWordsVectorizer(min_word_frequency=2).fit(self.DOCS)
        assert "cat" not in v.vocab  # appears once
        assert "the" in v.vocab and "sat" in v.vocab


class TestParagraphVectors:
    """Covers the batched fit path (DBOW and DM) + infer_vector
    (reference: `ParagraphVectorsTest.java` — doc vectors of same-topic
    documents end up closer than cross-topic)."""

    def _docs(self):
        from deeplearning4j_tpu.nlp.sentence_iterator import LabelledDocument

        rng = np.random.RandomState(7)
        docs = []
        for i in range(30):
            animal = ["cat", "dog", "pet", "fur", "paw"]
            vehicle = ["car", "bus", "road", "wheel", "engine"]
            pool = animal if i % 2 == 0 else vehicle
            words = [pool[rng.randint(len(pool))] for _ in range(40)]
            docs.append(LabelledDocument(" ".join(words),
                                         [f"doc_{i}"]))
        return docs

    @pytest.mark.parametrize("dm", [False, True], ids=["dbow", "dm"])
    def test_same_topic_docs_closer(self, dm):
        from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors

        pv = ParagraphVectors(self._docs(), dm=dm, layer_size=24,
                              window_size=3, epochs=12, seed=3,
                              batch_size=512).fit()
        v0 = pv.get_doc_vector("doc_0")   # animal
        v2 = pv.get_doc_vector("doc_2")   # animal
        v1 = pv.get_doc_vector("doc_1")   # vehicle

        def cos(a, b):
            return float(np.dot(a, b) /
                         (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))

        assert cos(v0, v2) > cos(v0, v1), (cos(v0, v2), cos(v0, v1))

    def test_infer_vector_lands_near_topic(self):
        from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors

        pv = ParagraphVectors(self._docs(), layer_size=24, window_size=3,
                              epochs=12, seed=3, batch_size=512).fit()
        inferred = pv.infer_vector("cat dog pet fur paw cat dog pet")
        near = pv.nearest_labels(inferred, 4)
        # Majority of nearest docs should be animal-topic (even doc ids).
        even = sum(1 for d in near if int(d.split("_")[1]) % 2 == 0)
        assert even >= 3, near


class TestDistributedWord2Vec:
    """Distributed embedding training (reference: the Spark NLP module's
    Word2Vec): flush batches shard over the mesh's data axis, GSPMD
    all-reduces the scatter-added updates — results must match the
    single-device run exactly (same batches, same order, float-assoc only)."""

    def _corpus(self):
        rng = np.random.RandomState(4)
        words = [f"w{i}" for i in range(50)]
        return [[words[rng.randint(50)] for _ in range(60)]
                for _ in range(30)]

    @pytest.mark.parametrize("mode", ["hs_sg", "hs_cbow", "ns_sg", "ns_cbow"])
    def test_mesh_matches_single_device(self, mode):
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec
        from deeplearning4j_tpu.parallel import mesh as mesh_mod

        kw = dict(layer_size=16, window_size=3, min_word_frequency=1,
                  seed=5, epochs=2, batch_size=256,
                  cbow="cbow" in mode,
                  negative=5 if mode.startswith("ns") else 0)
        corpus = self._corpus()
        ref = Word2Vec(**kw).fit(corpus)
        mesh = mesh_mod.create_mesh((8,), axis_names=("data",))
        dist = Word2Vec(mesh=mesh, **kw).fit(corpus)
        np.testing.assert_allclose(np.asarray(dist.syn0),
                                   np.asarray(ref.syn0),
                                   rtol=2e-4, atol=1e-6)

    def test_rejects_indivisible_batch(self):
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec
        from deeplearning4j_tpu.parallel import mesh as mesh_mod

        mesh = mesh_mod.create_mesh((8,), axis_names=("data",))
        with pytest.raises(ValueError, match="divisible"):
            Word2Vec(batch_size=100, mesh=mesh).fit([["a", "b", "c"]])


class TestNativeVocab:
    """`native/fastvocab.cpp` vs the Python VocabConstructor path: the
    native builder must be byte-for-byte identical or refuse (None)."""

    def _python_ref(self, sentences, min_freq, factory=None):
        from deeplearning4j_tpu.nlp.tokenization import (
            TokenizerFactory, tokenize_corpus,
        )
        from deeplearning4j_tpu.nlp.vocab import VocabConstructor

        corpus = tokenize_corpus(sentences, factory or TokenizerFactory())
        vocab = VocabConstructor(min_freq).build(corpus)
        seqs = [[vocab.index_of(t) for t in seq if vocab.contains_word(t)]
                for seq in corpus]
        return ([w.word for w in vocab._by_index],
                [w.frequency for w in vocab._by_index], seqs)

    def test_matches_python_presplit(self):
        from deeplearning4j_tpu import native as native_mod

        if native_mod._lib("fastvocab") is None:
            pytest.skip("no toolchain")
        sents = [["b", "a", "b", "c"], ["a", "b"], [], ["zz", "a", "a"],
                 ["tie1", "tie2"]]  # ties sort lexicographically
        got = native_mod.build_vocab_corpus(sents, 1.0)
        assert got is not None
        words, counts, seqs = got
        w_ref, c_ref, s_ref = self._python_ref(sents, 1)
        assert words == w_ref
        assert counts.tolist() == c_ref
        assert [s.tolist() for s in seqs] == s_ref
        # min_freq filter drops singletons identically (OOV skipped).
        got2 = native_mod.build_vocab_corpus(sents, 2.0)
        w2, c2, s2 = got2
        w_ref2, c_ref2, s_ref2 = self._python_ref(sents, 2)
        assert w2 == w_ref2 and [s.tolist() for s in s2] == s_ref2

    def test_matches_python_raw_with_preprocessor(self):
        from deeplearning4j_tpu import native as native_mod
        from deeplearning4j_tpu.nlp.tokenization import (
            CommonPreprocessor, TokenizerFactory,
        )

        if native_mod._lib("fastvocab") is None:
            pytest.skip("no toolchain")
        factory = TokenizerFactory(CommonPreprocessor())
        sents = ["The QUICK brown fox, 42 times!",
                 "the (quick) dog...   and\tthe fox",
                 "1234 ,,, !!!"]  # tokens that strip to nothing
        got = native_mod.build_vocab_corpus(sents, 1.0, factory)
        assert got is not None
        words, counts, seqs = got
        w_ref, c_ref, s_ref = self._python_ref(sents, 1, factory)
        assert words == w_ref
        assert counts.tolist() == c_ref
        assert [s.tolist() for s in seqs] == s_ref

    def test_exactness_guards_refuse(self):
        from deeplearning4j_tpu import native as native_mod
        from deeplearning4j_tpu.nlp.tokenization import (
            CommonPreprocessor, EndingPreProcessor, TokenizerFactory,
        )

        if native_mod._lib("fastvocab") is None:
            pytest.skip("no toolchain")
        # Non-ASCII with the preprocessor: Python lower() is unicode-aware.
        assert native_mod.build_vocab_corpus(
            ["Füchse sind schlau"], 1.0,
            TokenizerFactory(CommonPreprocessor())) is None
        # Unsupported preprocessor.
        assert native_mod.build_vocab_corpus(
            ["plain text"], 1.0,
            TokenizerFactory(EndingPreProcessor())) is None
        # Pre-split token containing the separator byte.
        assert native_mod.build_vocab_corpus(
            [["ok", "bad token"]], 1.0) is None
        # Raw sentence containing an embedded newline.
        assert native_mod.build_vocab_corpus(["a b\nc d"], 1.0) is None
        # Mixed str/list corpus.
        assert native_mod.build_vocab_corpus(["a b", ["c"]], 1.0) is None
        # Non-ASCII PRE-SPLIT tokens are fine (UTF-8 byte order == code
        # point order for the sort tie-break).
        got = native_mod.build_vocab_corpus([["é", "a", "é"]], 1.0)
        assert got is not None and got[0] == ["é", "a"]

    def test_word2vec_fit_uses_fast_path_same_result(self):
        """End-to-end: Word2Vec trained via the native vocab path equals a
        run forced onto the Python path (same vocab -> same kernels)."""
        from deeplearning4j_tpu import native as native_mod
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec

        if native_mod._lib("fastvocab") is None:
            pytest.skip("no toolchain")
        rng = np.random.RandomState(0)
        words = [f"w{i}" for i in range(50)]
        sents = [[words[j] for j in rng.randint(0, 50, 30)]
                 for _ in range(40)]
        kw = dict(layer_size=16, window_size=3, min_word_frequency=2,
                  sample=0, negative=0, seed=3, batch_size=256)
        m1 = Word2Vec(**kw).fit(sents)

        real = native_mod.build_vocab_corpus
        native_mod.build_vocab_corpus = lambda *a, **k: None
        try:
            m2 = Word2Vec(**kw).fit(sents)
        finally:
            native_mod.build_vocab_corpus = real
        assert m1.vocab.words() == m2.vocab.words()
        np.testing.assert_allclose(np.asarray(m1.syn0), np.asarray(m2.syn0),
                                   rtol=1e-6, atol=1e-7)


class TestDistributedCorpus:
    """Single-process sanity for `nlp/distributed_corpus.py` (the 2-process
    run lives in test_distributed.py): with one shard, the distributed
    pipeline must equal the local VocabConstructor/CoOccurrences path."""

    def test_vocab_matches_local(self):
        from deeplearning4j_tpu.nlp.distributed_corpus import distributed_vocab
        from deeplearning4j_tpu.nlp.tokenization import (
            TokenizerFactory, tokenize_corpus,
        )
        from deeplearning4j_tpu.nlp.vocab import VocabConstructor

        sents = [["b", "a", "b"], ["c", "a", "d", "a"], ["rare"]]
        vocab, seqs = distributed_vocab(sents, min_word_frequency=2)
        ref = VocabConstructor(2).build(
            tokenize_corpus(sents, TokenizerFactory()))
        assert vocab.words() == ref.words()
        assert [w.frequency for w in vocab._by_index] == \
            [w.frequency for w in ref._by_index]
        # Huffman codes assigned identically.
        assert [w.codes for w in vocab._by_index] == \
            [w.codes for w in ref._by_index]
        want = [[ref.index_of(t) for t in s if ref.contains_word(t)]
                for s in sents]
        assert [s.tolist() for s in seqs] == want

    def test_cooccurrences_match_local(self):
        from deeplearning4j_tpu.nlp.distributed_corpus import (
            distributed_cooccurrences,
        )
        from deeplearning4j_tpu.nlp.glove import CoOccurrences

        seqs = [np.asarray([0, 1, 2, 1, 0], np.int32),
                np.asarray([3, 2, 1], np.int32)]
        r, c, v = distributed_cooccurrences(seqs, window_size=2)
        rr, cc, vv = CoOccurrences(2, True).count(seqs)
        got = {(int(a), int(b)): float(w) for a, b, w in zip(r, c, v)}
        want = {(int(a), int(b)): float(w) for a, b, w in zip(rr, cc, vv)}
        assert got.keys() == want.keys()
        for k in want:
            assert abs(got[k] - want[k]) < 1e-6


def test_native_vocab_separator_control_chars():
    """\\x1c-\\x1f are Python str.split() whitespace: the native raw-string
    path must split identically ('a\\x1cb'.split() == ['a', 'b'])."""
    from deeplearning4j_tpu import native as native_mod
    from deeplearning4j_tpu.nlp.tokenization import (
        TokenizerFactory, tokenize_corpus,
    )
    from deeplearning4j_tpu.nlp.vocab import VocabConstructor

    if native_mod._lib("fastvocab") is None:
        pytest.skip("no toolchain")
    sents = ["a\x1cb c", "b\x1d\x1e a\x1f"]
    got = native_mod.build_vocab_corpus(sents, 1.0, TokenizerFactory())
    assert got is not None
    ref = VocabConstructor(1).build(
        tokenize_corpus(sents, TokenizerFactory()))
    assert got[0] == ref.words()
    # Pre-split tokens CONTAINING these bytes diverge from the joined-buffer
    # encoding; the token-count guard must refuse.
    assert native_mod.build_vocab_corpus([["a\x1cb"]], 1.0) is None
