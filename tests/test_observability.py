"""Observability stack tests: StatsListener -> storage -> UIServer.

Reference analogs: `BaseStatsListener.java:43,273` (stats collection),
`InMemoryStatsStorage`/`FileStatsStorage` (`api/storage/impl/`), the Play
UI's train-module JSON routes (`TrainModule.java:92-99`), and the
TrainingListener epoch hooks (`optimize/api/TrainingListener.java`).

These exercise the engines' `train_step_stats` jit variants in CI (the
stats pytree shape is load-bearing for the UI) and the epoch-hook dispatch
from both engines' fit().
"""

import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.api.storage import (
    FileStatsStorage,
    InMemoryStatsStorage,
)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    DenseLayer,
    LSTM,
    OutputLayer,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.listeners import IterationListener
from deeplearning4j_tpu.ui.server import UIServer
from deeplearning4j_tpu.ui.stats import ProfilerListener, StatsListener


def mlp_net():
    conf = (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.1).updater("sgd")
            .list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


def graph_net():
    gb = (NeuralNetConfiguration.builder()
          .seed(7).learning_rate(0.1).updater("sgd")
          .graph_builder()
          .add_inputs("in")
          .add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
          .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                        loss_function="mcxent"), "d")
          .set_outputs("out"))
    gb.set_input_types(InputType.feed_forward(4))
    return ComputationGraph(gb.build()).init()


def batch(rng, b=16):
    x = rng.randn(b, 4).astype("float32")
    y = np.eye(3)[rng.randint(0, 3, b)].astype("float32")
    return x, y


class TestStatsListener:
    def test_mln_records_content(self, rng):
        storage = InMemoryStatsStorage()
        net = mlp_net()
        net.set_listeners(StatsListener(storage, frequency=1,
                                        session_id="s1"))
        assert net._collect_stats  # stats jit variant engaged
        x, y = batch(rng)
        for _ in range(3):
            net.fit(x, y)

        info = storage.get_static_info("s1")
        assert info["model_class"] == "MultiLayerNetwork"
        assert info["num_params"] == net.num_params()
        updates = storage.get_updates("s1")
        assert len(updates) == 3
        rec = updates[-1]
        assert np.isfinite(rec["score"])
        # In-jit mean magnitudes for every param of every trainable layer.
        ls = rec["layer_stats"]
        for lk in net.layer_keys:
            if net.params_tree.get(lk):
                for pn in net.params_tree[lk]:
                    for stat in ("grad_mm", "update_mm", "param_mm"):
                        assert np.isfinite(ls[lk][pn][stat])
        # Histograms cover the same params.
        assert any(k.endswith("/W") for k in rec["param_histograms"])
        counts = next(iter(rec["param_histograms"].values()))["counts"]
        assert sum(counts) > 0

    def test_graph_records_content(self, rng):
        storage = InMemoryStatsStorage()
        net = graph_net()
        net.set_listeners(StatsListener(storage, frequency=1,
                                        session_id="g1",
                                        collect_histograms=False))
        x, y = batch(rng)
        for _ in range(2):
            net.fit(x, y)
        rec = storage.get_latest_update("g1")
        assert rec["layer_stats"]["d"]["W"]["grad_mm"] >= 0
        assert rec["layer_stats"]["out"]["W"]["update_mm"] >= 0

    def test_tbptt_stats_collected(self, rng):
        """tBPTT training must feed StatsListener too (ADVICE r2: the tbptt
        jit previously never collected, leaving stale/no stats)."""
        conf = (NeuralNetConfiguration.builder()
                .seed(7).learning_rate(0.05).updater("sgd")
                .list()
                .layer(LSTM(n_out=6, activation="tanh"))
                .layer(RnnOutputLayer(n_out=3, activation="softmax",
                                      loss_function="mcxent"))
                .set_input_type(InputType.recurrent(4, 12))
                .backprop_type("truncatedbptt")
                .t_bptt_forward_length(4).t_bptt_backward_length(4)
                .build())
        net = MultiLayerNetwork(conf).init()
        storage = InMemoryStatsStorage()
        net.set_listeners(StatsListener(storage, frequency=1,
                                        session_id="t1",
                                        collect_histograms=False))
        x = rng.randn(2, 12, 4).astype("float32")
        y = np.eye(3)[rng.randint(0, 3, (2, 12))].astype("float32")
        net.fit(x, y)
        rec = storage.get_latest_update("t1")
        ls = rec["layer_stats"]
        assert np.isfinite(ls["layer_0"]["W"]["grad_mm"])
        assert np.isfinite(ls["layer_1"]["W"]["update_mm"])


class TestFileStatsStorage:
    def test_jsonl_roundtrip(self, tmp_path, rng):
        path = str(tmp_path / "stats.jsonl")
        storage = FileStatsStorage(path)
        storage.put_static_info({"session_id": "f1", "worker_id": "w0",
                                 "model_class": "X", "num_params": 3})
        storage.put_update({"session_id": "f1", "iteration": 1, "score": 0.5})
        storage.put_update({"session_id": "f1", "iteration": 2, "score": 0.4})

        # Fresh instance reads back what the first wrote (restart survival).
        readback = FileStatsStorage(path)
        assert readback.list_session_ids() == ["f1"]
        assert readback.get_static_info("f1")["num_params"] == 3
        ups = readback.get_updates("f1")
        assert [u["iteration"] for u in ups] == [1, 2]
        assert readback.get_latest_update("f1")["score"] == 0.4
        # Every line is valid JSON with a timestamp.
        with open(path) as f:
            for line in f:
                assert "timestamp" in json.loads(line)

    def test_listener_through_file_storage(self, tmp_path, rng):
        storage = FileStatsStorage(str(tmp_path / "s.jsonl"))
        net = mlp_net()
        net.set_listeners(StatsListener(storage, frequency=1, session_id="f2",
                                        collect_histograms=False))
        x, y = batch(rng)
        net.fit(x, y)
        assert storage.get_latest_update("f2")["iteration"] == 1


class TestUIServer:
    def test_endpoints_over_http(self, rng):
        storage = InMemoryStatsStorage()
        net = mlp_net()
        net.set_listeners(StatsListener(storage, frequency=1, session_id="u1",
                                        collect_histograms=False))
        x, y = batch(rng)
        net.fit(x, y)

        server = UIServer(port=0).attach(storage).start()
        try:
            def get(path):
                with urllib.request.urlopen(server.url.rstrip("/") + path,
                                            timeout=5) as r:
                    return r.status, r.read()

            status, body = get("/api/sessions")
            assert status == 200 and json.loads(body) == ["u1"]
            status, body = get("/api/static?sid=u1")
            assert json.loads(body)["model_class"] == "MultiLayerNetwork"
            status, body = get("/api/updates?sid=u1")
            ups = json.loads(body)
            assert len(ups) == 1 and np.isfinite(ups[0]["score"])
            status, body = get("/")
            assert status == 200 and b"training UI" in body
            status, _ = urllib.request.urlopen(
                server.url.rstrip("/") + "/api/sessions", timeout=5).status, None
        finally:
            server.stop()

    def test_unknown_path_404(self):
        server = UIServer(port=0).attach(InMemoryStatsStorage()).start()
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(server.url.rstrip("/") + "/nope",
                                       timeout=5)
            assert ei.value.code == 404
        finally:
            server.stop()


class TestProfilerListener:
    def test_trace_dir_created(self, tmp_path, rng):
        log_dir = str(tmp_path / "trace")
        net = mlp_net()
        net.set_listeners(ProfilerListener(log_dir, start_iteration=2,
                                           num_iterations=2))
        x, y = batch(rng)
        for _ in range(6):
            net.fit(x, y)
        import glob
        import os
        assert os.path.isdir(log_dir)
        assert glob.glob(os.path.join(log_dir, "**", "*.xplane.pb"),
                         recursive=True), "no xplane trace written"


class _EpochSpy(IterationListener):
    def __init__(self):
        self.starts = 0
        self.ends = 0
        self.iters = 0

    def on_epoch_start(self, model):
        self.starts += 1

    def on_epoch_end(self, model):
        self.ends += 1

    def iteration_done(self, model, iteration):
        self.iters += 1


class TestEpochHooks:
    def test_mln_dispatches_epoch_hooks(self, rng):
        net = mlp_net()
        spy = _EpochSpy()
        net.set_listeners(spy)
        x, y = batch(rng)
        ds = DataSet(x, y)
        net.fit([ds, ds])   # one epoch, two batches
        net.fit([ds])       # second epoch
        assert spy.starts == 2
        assert spy.ends == 2
        assert spy.iters == 3

    def test_graph_dispatches_epoch_hooks(self, rng):
        net = graph_net()
        spy = _EpochSpy()
        net.set_listeners(spy)
        x, y = batch(rng)
        net.fit(x, y)
        assert spy.starts == 1 and spy.ends == 1 and spy.iters == 1


class TestSystemPage:
    def test_system_page_and_host_rss(self, rng):
        """The /system page serves, and update records carry host RSS +
        device memory (reference: TrainModule system tab +
        BaseStatsListener memory reporting)."""
        import urllib.request

        from deeplearning4j_tpu.api.storage import InMemoryStatsStorage
        from deeplearning4j_tpu.ui.server import UIServer
        from deeplearning4j_tpu.ui.stats import StatsListener

        storage = InMemoryStatsStorage()
        net = mlp_net()
        net.set_listeners(StatsListener(storage, frequency=1,
                                        collect_histograms=False))
        X, Y = batch(rng)
        for _ in range(3):
            net.fit(X, Y)
        sid = storage.list_session_ids()[0]
        ups = storage.get_updates(sid)
        assert any("host_rss_mb" in u and u["host_rss_mb"] > 0 for u in ups)
        server = UIServer(port=0).attach(storage).start()
        try:
            html = urllib.request.urlopen(server.url + "/system",
                                          timeout=10).read().decode()
            assert "Device memory" in html and "host_rss_mb" in html
        finally:
            server.stop()


class TestRound5UIModules:
    """The three reference UI modules added in round 5: flow (network
    graph), t-SNE, convolutional activations — pages render and their data
    routes serve live content during a fit (reference:
    `deeplearning4j-play/.../ui/module/{flow,tsne,convolutional}/`)."""

    def _get(self, server, path):
        with urllib.request.urlopen(server.url.rstrip("/") + path,
                                    timeout=5) as r:
            return r.status, r.read()

    def test_flow_page_and_graph_json(self, rng):
        storage = InMemoryStatsStorage()
        net = mlp_net()
        net.set_listeners(StatsListener(storage, frequency=1,
                                        session_id="f1",
                                        collect_histograms=False))
        x, y = batch(rng)
        net.fit(x, y)
        server = UIServer(port=0).attach(storage).start()
        try:
            status, body = self._get(server, "/flow")
            assert status == 200 and b"Network graph" in body
            # The page's data source: static info must carry the config
            # JSON the layout() JS walks.
            _, body = self._get(server, "/api/static?sid=f1")
            conf = json.loads(json.loads(body)["model_config_json"])
            assert "layers" in conf or "vertices" in conf
        finally:
            server.stop()

    def test_tsne_page_and_upload(self, rng):
        from deeplearning4j_tpu.plot.tsne import Tsne

        X = np.concatenate([rng.randn(15, 4), rng.randn(15, 4) + 6.0])
        Y = Tsne(max_iter=30, perplexity=5.0).fit_transform(X)
        labels = [0] * 15 + [1] * 15

        server = UIServer(port=0, enable_remote=True).attach(
            InMemoryStatsStorage()).start()
        try:
            server.upload_tsne(Y, labels=labels, name="test-embedding")
            status, body = self._get(server, "/tsne")
            assert status == 200 and b"t-SNE" in body
            _, body = self._get(server, "/api/tsne")
            data = json.loads(body)
            assert len(data["coords"]) == 30 and data["labels"] == labels
            # HTTP upload path too (the reference's file-upload analog).
            req = urllib.request.Request(
                server.url.rstrip("/") + "/api/tsne",
                data=json.dumps({"coords": [[0.0, 1.0], [1.0, 0.0]],
                                 "labels": ["a", "b"]}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5) as r:
                assert json.loads(r.read())["n"] == 2
            _, body = self._get(server, "/api/tsne")
            assert len(json.loads(body)["coords"]) == 2
        finally:
            server.stop()

    def test_activations_page_live_during_fit(self, rng):
        from deeplearning4j_tpu.nn.conf.layers import (
            ConvolutionLayer, OutputLayer, SubsamplingLayer,
        )
        from deeplearning4j_tpu.ui.stats import ConvolutionalListener

        conf = (NeuralNetConfiguration.builder()
                .seed(4).learning_rate(0.01).updater("adam")
                .list()
                .layer(ConvolutionLayer(n_out=6, kernel_size=3,
                                        convolution_mode="same",
                                        activation="relu"))
                .layer(SubsamplingLayer(kernel_size=2, stride=2))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss_function="mcxent"))
                .set_input_type(InputType.convolutional(12, 12, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        storage = InMemoryStatsStorage()
        probe = rng.rand(1, 12, 12, 1).astype("float32")
        net.set_listeners(
            StatsListener(storage, frequency=1, session_id="c1",
                          collect_histograms=False),
            ConvolutionalListener(storage, probe, frequency=1,
                                  session_id="c1"))
        x = rng.rand(8, 12, 12, 1).astype("float32")
        y = np.eye(3, dtype="float32")[rng.randint(0, 3, 8)]
        net.fit(DataSet(x, y))

        server = UIServer(port=0).attach(storage).start()
        try:
            status, body = self._get(server, "/activations")
            assert status == 200 and b"Convolutional activations" in body
            _, body = self._get(server, "/api/updates?sid=c1")
            ups = json.loads(body)
            conv = [u for u in ups if "conv_activations" in u]
            assert conv, "no activation sample reached storage"
            grids = conv[-1]["conv_activations"]
            assert "layer_0" in grids
            g = grids["layer_0"]
            assert len(g["channels"]) == 6
            assert len(g["channels"][0]) == g["h"] * g["w"]
        finally:
            server.stop()


def test_tsne_post_gated_by_enable_remote():
    """HTTP t-SNE writes follow the same explicit-enable policy as /remote."""
    server = UIServer(port=0).attach(InMemoryStatsStorage()).start()
    try:
        req = urllib.request.Request(
            server.url.rstrip("/") + "/api/tsne",
            data=json.dumps({"coords": [[0.0, 1.0]]}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 403
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# Observability core (deeplearning4j_tpu/observability/): metrics registry,
# span tracing, StepProfiler, and the wired-through endpoints.
# ---------------------------------------------------------------------------


def _http_get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.read()


class TestMetricsRegistry:
    def test_counter_gauge_histogram_values(self):
        from deeplearning4j_tpu.observability.metrics import MetricsRegistry

        reg = MetricsRegistry()
        c = reg.counter("req_total", "requests", label_names=("code",))
        c.labels(code="200").inc()
        c.labels(code="200").inc(2)
        c.labels(code="500").inc()
        assert c.labels(code="200").get() == 3
        assert c.labels(code="500").get() == 1
        g = reg.gauge("depth", "queue depth")
        g.set(7)
        assert g.get() == 7
        h = reg.histogram("lat", "latency", buckets=(0.1, 1.0))
        for v in (0.05, 0.5, 5.0):
            h.observe(v)
        buckets, cum, s, count = h._only().histogram_state()
        assert buckets == (0.1, 1.0)
        assert cum == [1, 2, 3] and count == 3
        assert abs(s - 5.55) < 1e-9

    def test_prometheus_text_format_conformance(self):
        """Text format 0.0.4: HELP/TYPE lines, escaped label values,
        cumulative _bucket series ending at +Inf == _count, _sum/_count."""
        from deeplearning4j_tpu.observability.metrics import MetricsRegistry

        reg = MetricsRegistry()
        c = reg.counter("odd_total", "counts odd things",
                        label_names=("name",))
        c.labels(name='a"b\\c\nd').inc()
        h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        text = reg.to_prometheus()
        lines = text.strip().split("\n")
        assert "# HELP odd_total counts odd things" in lines
        assert "# TYPE odd_total counter" in lines
        # Escaping: backslash, double-quote, newline within the label value.
        assert 'odd_total{name="a\\"b\\\\c\\nd"} 1' in lines
        assert "# TYPE lat_seconds histogram" in lines
        assert 'lat_seconds_bucket{le="0.1"} 1' in lines
        assert 'lat_seconds_bucket{le="1"} 2' in lines
        assert 'lat_seconds_bucket{le="+Inf"} 2' in lines
        assert any(l.startswith("lat_seconds_sum ") for l in lines)
        assert "lat_seconds_count 2" in lines
        # Buckets are cumulative and non-decreasing.
        vals = [int(l.rsplit(" ", 1)[1]) for l in lines
                if l.startswith("lat_seconds_bucket")]
        assert vals == sorted(vals)

    def test_family_dedupe_and_kind_mismatch(self):
        from deeplearning4j_tpu.observability.metrics import MetricsRegistry

        reg = MetricsRegistry()
        a = reg.counter("x_total", "x", label_names=("k",))
        b = reg.counter("x_total", "ignored", label_names=("k",))
        assert a is b
        with pytest.raises(ValueError):
            reg.gauge("x_total")
        with pytest.raises(ValueError):
            reg.counter("x_total", label_names=("other",))
        with pytest.raises(ValueError):
            reg.counter("bad name")
        with pytest.raises(ValueError):
            a.labels(wrong="v")

    def test_json_snapshot_and_summary(self):
        from deeplearning4j_tpu.observability.metrics import MetricsRegistry

        reg = MetricsRegistry()
        h = reg.histogram("s_seconds", "steps", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.2, 0.3, 2.0):
            h.observe(v)
        snap = reg.to_json()
        series = snap["s_seconds"]["series"][0]
        assert series["count"] == 4
        summary = series["summary"]
        assert summary["count"] == 4 and summary["mean"] == pytest.approx(
            2.55 / 4)
        assert 0 < summary["p50"] <= 1.0
        reg.reset()  # values drop to zero; the family itself survives
        assert reg.to_json()["s_seconds"]["series"][0]["count"] == 0

    def test_scrape_time_gauge_and_collector(self):
        from deeplearning4j_tpu.observability.metrics import MetricsRegistry

        reg = MetricsRegistry()
        g = reg.gauge("fn_gauge", "from function")
        g.set_function(lambda: 42.0)
        calls = []
        reg.register_collector(lambda r: calls.append(1))
        text = reg.to_prometheus()
        assert "fn_gauge 42" in text
        assert calls  # collector ran at scrape

    def test_disabled_registry_records_nothing(self):
        from deeplearning4j_tpu.observability.metrics import MetricsRegistry

        reg = MetricsRegistry(enabled=False)
        c = reg.counter("n_total")
        c.inc(5)
        h = reg.histogram("h_seconds")
        h.observe(1.0)
        assert c.get() == 0
        assert h._only().histogram_state()[3] == 0


class TestDisabledOverhead:
    def test_noop_path_is_cheap(self):
        """The ISSUE 2 bar: a disabled registry/tracer adds < a few µs per
        call. Budget is generous (10µs) for noisy shared CI machines; the
        real cost is one attribute load + bool check (~0.1µs)."""
        import time as _t

        from deeplearning4j_tpu.observability.metrics import MetricsRegistry
        from deeplearning4j_tpu.observability.tracing import Tracer

        reg = MetricsRegistry(enabled=False)
        c = reg.counter("o_total")
        h = reg.histogram("o_seconds")
        tr = Tracer(enabled=False)
        n = 20000
        t0 = _t.perf_counter()
        for _ in range(n):
            c.inc()
            h.observe(0.5)
            with tr.span("x"):
                pass
        per_call = (_t.perf_counter() - t0) / (3 * n)
        assert per_call < 10e-6, f"{per_call * 1e6:.2f}µs per disabled call"
        assert c.get() == 0 and not tr.events()


class TestTracing:
    def test_nested_spans_and_chrome_export(self):
        from deeplearning4j_tpu.observability.tracing import Tracer

        tr = Tracer()
        with tr.span("outer", cat="test"):
            with tr.span("inner", cat="test", k="v"):
                pass
        doc = tr.export_chrome()
        # Valid Chrome trace JSON: round-trips and has the required fields.
        doc2 = json.loads(json.dumps(doc))
        assert doc2["traceEvents"]
        by_name = {e["name"]: e for e in doc2["traceEvents"]}
        inner, outer = by_name["inner"], by_name["outer"]
        for e in (inner, outer):
            assert e["ph"] == "X"
            assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert inner["args"]["parent"] == "outer"
        assert inner["args"]["k"] == "v"
        assert "parent" not in outer["args"]
        # Inner is contained within outer on the timeline.
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3

    def test_ring_buffer_bounded(self):
        from deeplearning4j_tpu.observability.tracing import Tracer

        tr = Tracer(max_events=16)
        for i in range(100):
            with tr.span(f"s{i}"):
                pass
        events = tr.events()
        assert len(events) == 16
        assert events[-1]["name"] == "s99"  # newest kept, oldest dropped

    def test_decorator_error_attr_and_instant(self):
        from deeplearning4j_tpu.observability.tracing import Tracer

        tr = Tracer()

        @tr.trace("worker")
        def work():
            return 5

        assert work() == 5
        with pytest.raises(ValueError):
            with tr.span("boom"):
                raise ValueError("x")
        tr.instant("marker", note="here")
        names = {e["name"]: e for e in tr.events()}
        assert "worker" in names
        assert names["boom"]["args"]["error"] == "ValueError"
        assert names["marker"]["ph"] == "i"


class TestComposableEpochHooks:
    def test_composable_fans_out_epoch_hooks(self, rng):
        """Regression lock for the ISSUE 2 satellite: composed listeners
        must see on_epoch_start/on_epoch_end, not just iteration_done."""
        from deeplearning4j_tpu.optimize.listeners import (
            ComposableIterationListener,
        )

        class Recorder(IterationListener):
            def __init__(self):
                self.calls = []

            def iteration_done(self, model, iteration):
                self.calls.append("iter")

            def on_epoch_start(self, model):
                self.calls.append("start")

            def on_epoch_end(self, model):
                self.calls.append("end")

        a, b = Recorder(), Recorder()
        net = mlp_net()
        net.set_listeners(ComposableIterationListener(a, b))
        x, y = batch(rng)
        net.fit(DataSet(x, y))
        for r in (a, b):
            assert r.calls == ["start", "iter", "end"]


class TestPerformanceListenerHonesty:
    def test_no_stale_samples_per_sec(self, rng):
        """An interval without record_batch must report NaN, not the
        previous interval's number."""
        from deeplearning4j_tpu.optimize.listeners import PerformanceListener

        listener = PerformanceListener(frequency=1, out=lambda s: None)
        net = mlp_net()
        net.set_listeners(listener)
        x, y = batch(rng)
        net.fit(DataSet(x, y))  # primes the clock
        listener.record_batch(16)
        net.fit(DataSet(x, y))
        assert listener.last_samples_per_sec > 0
        net.fit(DataSet(x, y))  # no record_batch this interval
        assert np.isnan(listener.last_samples_per_sec)
        assert listener.last_batches_per_sec > 0  # still per-iteration

    def test_sync_knob_settles_before_sampling(self, rng):
        from deeplearning4j_tpu.optimize.listeners import PerformanceListener

        msgs = []
        listener = PerformanceListener(frequency=1, sync=True,
                                       out=msgs.append)
        net = mlp_net()
        net.set_listeners(listener)
        x, y = batch(rng)
        for _ in range(3):
            net.fit(DataSet(x, y))
        assert msgs and listener.last_batches_per_sec > 0


class TestStepProfilerAcceptance:
    def test_smoke_run_metrics_and_trace(self, rng, tmp_path):
        """The ISSUE 2 acceptance smoke: fit a small MLP under StepProfiler
        with an in-fit checkpoint save, serve a request through
        InferenceServer, then assert the /metrics scrape carries the
        step-latency histogram, the compile-vs-execute split, checkpoint
        bytes, and request latency — and the exported trace nests
        fit -> iteration -> checkpoint."""
        from deeplearning4j_tpu import observability as obs
        from deeplearning4j_tpu.checkpoint import CheckpointManager
        from deeplearning4j_tpu.observability import StepProfiler
        from deeplearning4j_tpu.serving import InferenceServer

        obs.tracer.clear()
        net = mlp_net()
        mgr = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)

        class SaveAt(IterationListener):
            def iteration_done(self, model, iteration):
                if iteration == 3:
                    mgr.save(model, step=iteration)

        net.set_listeners(SaveAt())
        x, y = batch(rng)
        with StepProfiler(net, sync=True) as prof:
            for _ in range(5):
                net.fit(DataSet(x, y))

        # One adam-updated fit so the kernel dispatch seam (ISSUE 10)
        # resolves `fused_update` and its counter carries a child for the
        # scrape assertion below (mlp_net's sgd never enters the seam).
        adam_conf = (NeuralNetConfiguration.builder()
                     .seed(7).learning_rate(0.1).updater("adam")
                     .list()
                     .layer(DenseLayer(n_out=8, activation="tanh"))
                     .layer(OutputLayer(n_out=3, activation="softmax",
                                        loss_function="mcxent"))
                     .set_input_type(InputType.feed_forward(4))
                     .build())
        MultiLayerNetwork(adam_conf).init().fit(DataSet(x, y))
        summary = prof.summary()
        assert summary["steps"] == 5
        assert summary["first_call_steps"] >= 1
        assert summary["compile_seconds"] > 0
        assert summary["execute_seconds_median"] > 0
        assert summary["host_to_device_bytes"] > 0

        server = InferenceServer(net, port=0).start()
        # One LoRA tenant next to the resident base, exercised through one
        # adapter-routed predict, so the multi-tenant metric families
        # (ISSUE 16) carry children for the scrape assertion below.
        from deeplearning4j_tpu.nn.transfer import TransferLearning

        server.load_adapter(
            "tenant-a", net=TransferLearning(net).add_lora(rank=1).build())
        try:
            req = urllib.request.Request(
                server.url + "/predict",
                data=json.dumps({"data": x[:4].tolist()}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                preds = json.loads(r.read())["predictions"]
            assert len(preds) == 4
            req = urllib.request.Request(
                server.url + "/predict",
                data=json.dumps({"data": x[:4].tolist(),
                                 "adapter": "tenant-a"}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                assert len(json.loads(r.read())["predictions"]) == 4
            status, body = _http_get(server.url + "/metrics")
        finally:
            server.stop()
        assert status == 200
        scrape = body.decode()
        for needle in (
                "dl4j_step_latency_seconds_bucket",       # step histogram
                "dl4j_profiler_compile_seconds",          # compile vs ...
                "dl4j_profiler_execute_seconds_median",   # ... execute split
                "dl4j_checkpoint_bytes_written_total",    # checkpoint bytes
                "dl4j_request_latency_seconds_bucket",    # request histogram
                "dl4j_serving_batch_size_bucket",
                'dl4j_jit_cache_misses_total{engine="mln"}',
                "dl4j_kernel_dispatch_total{",    # kernel registry seam
                "dl4j_train_flops_per_step",
                "dl4j_program_hbm_bytes",                 # static HBM gauges
                "dl4j_input_wait_seconds_bucket",         # starvation split
                "dl4j_adapters_resident{",                # LoRA tenants ...
                "dl4j_adapter_requests_total{",           # ... routed
                'adapter="tenant-a"',
        ):
            assert needle in scrape, f"missing {needle} in /metrics"

        # Bucket-ladder audit: every histogram family with observations must
        # resolve the majority of them inside its finite ladder — a family
        # whose observations mostly clamp into +Inf is measuring nothing.
        for name, fam in obs.metrics.to_json().items():
            if fam["type"] != "histogram":
                continue
            for series in fam["series"]:
                count = series["count"]
                if not count:
                    continue
                finite = max(series["buckets"].values(), default=0)
                assert count - finite <= count / 2, (
                    f"{name}{series['labels']}: {count - finite}/{count} "
                    "observations beyond the largest finite bucket — widen "
                    "the ladder (WIDE_BUCKETS)")

        doc = json.loads(json.dumps(obs.tracer.export_chrome()))
        events = doc["traceEvents"]
        assert events
        for e in events:
            assert e["ph"] in ("X", "i")
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        edges = {(e["name"], e["args"].get("parent")) for e in events
                 if e["ph"] == "X"}
        assert ("mln.iteration", "mln.fit") in edges
        assert ("checkpoint.snapshot", "mln.iteration") in edges
        assert ("checkpoint.write", "mln.iteration") in edges
        assert any(e["name"] == "serving.batch" for e in events)


class TestUIServerObsRoutes:
    def test_metrics_trace_and_route_index(self):
        from deeplearning4j_tpu import observability as obs

        obs.metrics.counter("ui_probe_total", "probe").inc()
        with obs.tracer.span("ui.probe"):
            pass
        server = UIServer(port=0).attach(InMemoryStatsStorage()).start()
        base = server.url.rstrip("/")
        try:
            status, body = _http_get(base + "/metrics")
            assert status == 200
            assert "# TYPE ui_probe_total counter" in body.decode()
            status, body = _http_get(base + "/api/trace")
            doc = json.loads(body)
            assert any(e["name"] == "ui.probe" for e in doc["traceEvents"])
            status, body = _http_get(base + "/api/flight")
            flight = json.loads(body)
            assert {"enabled", "capacity", "records",
                    "dump_dir"} <= set(flight)
            status, body = _http_get(base + "/api/memory")
            memdoc = json.loads(body)
            assert {"programs", "live"} <= set(memdoc)
            status, body = _http_get(base + "/api")
            routes = json.loads(body)["routes"]
            assert "/metrics" in routes and "/api/trace" in routes
            assert "/api/flight" in routes and "/api/memory" in routes
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(base + "/definitely/not/a/route",
                                       timeout=5)
            assert ei.value.code == 404
            nf = json.loads(ei.value.read())
            assert nf["error"] == "not found"
            assert "/metrics" in nf["routes"]  # 404s advertise the index
        finally:
            server.stop()
