"""Superstep training tests: K train iterations fused into ONE dispatch
(`lax.scan` over stacked `[K, B, ...]` batches) must be bit-identical to K
sequential per-batch steps — RNG chain, BN running stats, masked losses, and
the true-length non-multiple-of-K tail included. Plus the block-forming
iterator, the device-cache cleanup satellite, fallback gates, and the
ParallelWrapper superstep path. PERF.md §13."""

import logging

import numpy as np
import pytest

import jax

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterators import (
    _M_CACHE_BYTES,
    DeviceCacheDataSetIterator,
    ListDataSetIterator,
    Superbatch,
    SuperbatchIterator,
    batch_signature,
    maybe_reset,
    stack_superbatch,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization,
    DenseLayer,
    DropoutLayer,
    OutputLayer,
)
from deeplearning4j_tpu.nn.conf.neural_net import MultiLayerConfiguration
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.optimize.listeners import (
    CollectScoresIterationListener,
    IterationListener,
)

from conftest import make_classification_data

N_IN, N_OUT = 4, 3


def mlp_conf(superstep_k=0, updater="adam", dropout=True, bn=True, **g):
    b = (NeuralNetConfiguration.builder()
         .seed(7).learning_rate(0.05).updater(updater).weight_init("xavier")
         .superstep_k(superstep_k))
    for name, v in g.items():
        b = getattr(b, name)(v)
    lb = b.list().layer(DenseLayer(n_out=8, activation="relu"))
    if bn:
        lb = lb.layer(BatchNormalization())
    if dropout:
        lb = lb.layer(DropoutLayer(dropout=0.5))
    lb = lb.layer(OutputLayer(n_out=N_OUT, activation="softmax",
                              loss_function="mcxent"))
    return lb.set_input_type(InputType.feed_forward(N_IN)).build()


def graph_conf(superstep_k=0):
    return (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.05).updater("adam").weight_init("xavier")
            .superstep_k(superstep_k)
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_out=8, activation="relu"), "in")
            .add_layer("drop", DropoutLayer(dropout=0.4), "d")
            .add_layer("out", OutputLayer(n_out=N_OUT, activation="softmax",
                                          loss_function="mcxent"), "drop")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(N_IN))
            .build())


def make_batches(rng, n_batches=7, batch=6, labels_mask=False):
    out = []
    for _ in range(n_batches):
        X, Y = make_classification_data(rng, n=batch, n_features=N_IN,
                                        n_classes=N_OUT, dtype="float32")
        lm = None
        if labels_mask:
            lm = (rng.rand(batch) < 0.7).astype("float32")
            lm[0] = 1.0  # at least one unmasked row per batch
        out.append(DataSet(X, Y, labels_mask=lm))
    return out


def assert_trees_identical(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def net_snapshot(net):
    return (net.params_tree, net.opt_state, net.state)


def superstep_programs(net):
    """Block lengths of the compiled `train_superstep` programs."""
    ks = []
    for key in net._jit_cache:
        if key[0] == "train_superstep":
            ks.extend(v for name, v in key[1] if name == "k")
    return sorted(ks)


# --------------------------------------------------------------------------
# SuperbatchIterator / block forming


class TestSuperbatchIterator:
    def test_blocks_and_true_length_tail(self, rng):
        batches = make_batches(rng, n_batches=7)
        blocks = list(SuperbatchIterator(batches, k=4, stage=False))
        assert [getattr(b, "k", 1) for b in blocks] == [4, 3]
        assert blocks[0].features.shape == (4, 6, N_IN)
        assert blocks[1].features.shape == (3, 6, N_IN)  # no padding

    def test_singleton_block_yields_original_item(self, rng):
        batches = make_batches(rng, n_batches=5)
        blocks = list(SuperbatchIterator(batches, k=4, stage=False))
        assert isinstance(blocks[0], Superbatch)
        assert blocks[1] is batches[4]  # tail of 1: the raw DataSet

    def test_signature_change_flushes(self, rng):
        a = make_batches(rng, n_batches=3, batch=6)
        b = make_batches(rng, n_batches=2, batch=5)  # different batch dim
        blocks = list(SuperbatchIterator(a + b, k=4, stage=False))
        assert [getattr(blk, "k", 1) for blk in blocks] == [3, 2]
        assert batch_signature(a[0]) != batch_signature(b[0])

    def test_byte_budget_lowers_effective_k(self, rng):
        batches = make_batches(rng, n_batches=8)
        per = sum(a.nbytes for a in (batches[0].features, batches[0].labels))
        it = SuperbatchIterator(batches, k=8, max_bytes=3 * per, stage=False)
        assert [b.k for b in it] == [3, 3, 2]

    def test_stacking_preserves_values_and_masks(self, rng):
        batches = make_batches(rng, n_batches=3, labels_mask=True)
        sb = stack_superbatch(batches, stage=False)
        for i, ds in enumerate(batches):
            np.testing.assert_array_equal(sb.features[i], ds.features)
            np.testing.assert_array_equal(sb.labels_mask[i], ds.labels_mask)
        assert sb.features_mask is None

    def test_multidataset_blocks(self, rng):
        X, Y = make_classification_data(rng, n=6, n_features=N_IN,
                                        n_classes=N_OUT, dtype="float32")
        mds = MultiDataSet(features=[X], labels=[Y])
        blocks = list(SuperbatchIterator([mds, mds, mds], k=2, stage=False))
        assert [getattr(b, "k", 1) for b in blocks] == [2, 1]
        assert blocks[1] is mds  # singleton tail: the raw MultiDataSet
        assert blocks[0].features[0].shape == (2, 6, N_IN)

    def test_staged_block_is_device_resident(self, rng):
        batches = make_batches(rng, n_batches=2)
        (sb,) = SuperbatchIterator(batches, k=2, stage=True)
        assert not isinstance(sb.features, np.ndarray)
        assert sb.features.shape == (2, 6, N_IN)

    def test_device_cached_epochs_restack_once(self, rng):
        base = DeviceCacheDataSetIterator(make_batches(rng, n_batches=4))
        it = SuperbatchIterator(base, k=2)
        first = list(it)
        blocks_obj = it._blocks
        second = list(it)
        assert it._blocks is blocks_obj  # no restack on a cached epoch
        assert all(a is b for a, b in zip(first, second))
        base.invalidate()
        list(it)
        assert it._blocks is not blocks_obj  # invalidate propagates


class TestMaybeReset:
    def test_list_has_no_reset(self):
        assert maybe_reset([1, 2]) is False

    def test_resettable_iterator(self, rng):
        it = ListDataSetIterator(make_batches(rng, n_batches=2))
        assert maybe_reset(it) is True

    def test_failing_reset_logged_not_raised(self, caplog):
        class Broken:
            def reset(self):
                raise RuntimeError("boom")

        class NotImpl:
            def reset(self):
                raise NotImplementedError

        with caplog.at_level(logging.WARNING,
                             logger="deeplearning4j_tpu.datasets.iterators"):
            assert maybe_reset(Broken()) is False
        assert any("reset() failed" in r.message for r in caplog.records)
        caplog.clear()
        with caplog.at_level(logging.WARNING,
                             logger="deeplearning4j_tpu.datasets.iterators"):
            assert maybe_reset(NotImpl()) is False  # silent: not resettable
        assert not caplog.records


# --------------------------------------------------------------------------
# Device-cache cleanup satellite


class TestDeviceCacheCleanup:
    def test_memory_error_drops_partial_stage_and_gauge(self, rng):
        batches = make_batches(rng, n_batches=4)
        per = sum(a.nbytes for a in (batches[0].features, batches[0].labels))
        before = _M_CACHE_BYTES.get()
        it = DeviceCacheDataSetIterator(batches, max_bytes=2 * per)
        with pytest.raises(MemoryError):
            list(it)
        assert it._cache is None
        assert _M_CACHE_BYTES.get() == before  # nothing leaked into the gauge

    def test_gauge_tracks_cache_lifecycle(self, rng):
        batches = make_batches(rng, n_batches=3)
        before = _M_CACHE_BYTES.get()
        it = DeviceCacheDataSetIterator(batches)
        list(it)
        assert _M_CACHE_BYTES.get() > before
        list(it)  # replay: no double count
        after_replay = _M_CACHE_BYTES.get()
        it.invalidate()
        assert _M_CACHE_BYTES.get() == before
        assert after_replay > before


# --------------------------------------------------------------------------
# MultiLayerNetwork equivalence


class TestMLNEquivalence:
    def fit_pair(self, rng, k, n_batches=7, **conf_kw):
        batches = make_batches(rng, n_batches=n_batches,
                               labels_mask=conf_kw.pop("labels_mask", False))
        ref = MultiLayerNetwork(mlp_conf(superstep_k=0, **conf_kw)).init()
        for ds in batches:
            ref.fit(ds)
        net = MultiLayerNetwork(mlp_conf(superstep_k=k, **conf_kw)).init()
        net.fit(batches)
        return ref, net

    def test_bit_identical_with_bn_dropout_and_tail(self, rng):
        """7 batches, K=4: the dropout RNG chain, BN running stats, adam
        opt_state, and the length-3 tail block all match bit-for-bit."""
        ref, net = self.fit_pair(rng, k=4)
        assert_trees_identical(net_snapshot(ref), net_snapshot(net))
        assert ref.iteration == net.iteration == 7
        assert superstep_programs(net) == [3, 4]  # true-length tail program

    def test_unrolled_program_close(self, rng, monkeypatch):
        """`DL4J_TPU_SUPERSTEP_SCAN=0` opts into the unrolled program shape
        (CPU conv speed — `nn/superstep.py`). XLA then optimizes across
        iterations, so results are float-close, not bit-identical."""
        monkeypatch.setenv("DL4J_TPU_SUPERSTEP_SCAN", "0")
        ref, net = self.fit_pair(rng, k=4)
        for x, y in zip(jax.tree_util.tree_leaves(net_snapshot(ref)),
                        jax.tree_util.tree_leaves(net_snapshot(net))):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=1e-5, atol=1e-6)
        assert any(("scan", False) in key[1] for key in net._jit_cache
                   if key[0] == "train_superstep")

    def test_bit_identical_masked_loss(self, rng):
        ref, net = self.fit_pair(rng, k=3, n_batches=6, labels_mask=True)
        assert_trees_identical(net_snapshot(ref), net_snapshot(net))

    def test_scores_match_per_batch(self, rng):
        ref, net = self.fit_pair(rng, k=4)
        assert float(ref.score_value) == float(net.score_value)

    def test_env_knob_overrides_conf(self, rng, monkeypatch):
        monkeypatch.setenv("DL4J_TPU_SUPERSTEP_K", "3")
        net = MultiLayerNetwork(mlp_conf(superstep_k=0)).init()
        assert net._superstep_k() == 3
        monkeypatch.setenv("DL4J_TPU_SUPERSTEP_K", "0")
        net2 = MultiLayerNetwork(mlp_conf(superstep_k=8)).init()
        assert net2._superstep_k() == 0
        monkeypatch.delenv("DL4J_TPU_SUPERSTEP_K")
        assert net2._superstep_k() == 8

    def test_gates_force_per_batch(self, rng):
        net = MultiLayerNetwork(mlp_conf(superstep_k=8, iterations=3)).init()
        assert net._superstep_k() == 0
        lbfgs = MultiLayerNetwork(
            mlp_conf(superstep_k=8, dropout=False, bn=False,
                     optimization_algo="lbfgs")).init()
        assert lbfgs._superstep_k() == 0

    def test_listener_fanout_order_and_scores(self, rng):
        """Listeners fire once per TRAIN ITERATION (K per dispatch), in
        iteration order, with the same scores as the per-batch loop."""
        batches = make_batches(rng, n_batches=5)

        def run(k):
            seen = []

            class Probe(IterationListener):
                def iteration_done(self, model, iteration):
                    seen.append((iteration, float(model.score_value)))

            collect = CollectScoresIterationListener(frequency=1)
            net = MultiLayerNetwork(mlp_conf(superstep_k=k)).init()
            net.set_listeners(Probe(), collect)
            net.fit(batches)
            return seen, collect.scores

        seq_seen, seq_scores = run(0)
        sup_seen, sup_scores = run(3)
        assert [i for i, _ in sup_seen] == [1, 2, 3, 4, 5]
        assert sup_seen == seq_seen
        assert sup_scores == seq_scores

    def test_stats_listener_falls_back_to_per_batch(self, rng):
        """A stats-collecting listener needs per-iteration host stats, so the
        engine must gate superstep off and still populate the snapshot."""
        from deeplearning4j_tpu.api.storage import InMemoryStatsStorage
        from deeplearning4j_tpu.ui.stats import StatsListener

        batches = make_batches(rng, n_batches=4)
        net = MultiLayerNetwork(mlp_conf(superstep_k=4)).init()
        net.set_listeners(StatsListener(InMemoryStatsStorage(), frequency=1))
        assert net._superstep_k() == 0
        net.fit(batches)
        assert superstep_programs(net) == []
        assert net.last_training_stats  # per-batch path collected stats

    def test_superstep_k_survives_json_roundtrip(self):
        conf = mlp_conf(superstep_k=6)
        restored = MultiLayerConfiguration.from_json(conf.to_json())
        assert restored.global_conf.superstep_k == 6

    def test_wrapper_cached_on_iterator(self, rng):
        net = MultiLayerNetwork(mlp_conf(superstep_k=2)).init()
        base = DeviceCacheDataSetIterator(make_batches(rng, n_batches=4))
        w1 = net._superstep_wrap(base, 2)
        assert net._superstep_wrap(base, 2) is w1
        assert net._superstep_wrap(base, 3) is not w1  # k changed


# --------------------------------------------------------------------------
# ComputationGraph equivalence


class TestGraphEquivalence:
    def test_bit_identical_with_tail(self, rng):
        batches = make_batches(rng, n_batches=5, batch=5)
        ref = ComputationGraph(graph_conf(superstep_k=0)).init()
        for ds in batches:
            ref.fit(ds)
        net = ComputationGraph(graph_conf(superstep_k=3)).init()
        net.fit(batches)
        assert_trees_identical(net_snapshot(ref), net_snapshot(net))
        assert ref.iteration == net.iteration == 5
        assert superstep_programs(net) == [2, 3]

    def test_multidataset_iterator(self, rng):
        batches = make_batches(rng, n_batches=4, batch=5)
        mds = [MultiDataSet(features=[d.features], labels=[d.labels])
               for d in batches]
        ref = ComputationGraph(graph_conf(superstep_k=0)).init()
        for m in mds:
            ref.fit(m)
        net = ComputationGraph(graph_conf(superstep_k=2)).init()
        net.fit(mds)
        assert_trees_identical(net_snapshot(ref), net_snapshot(net))


# --------------------------------------------------------------------------
# ParallelWrapper


class TestParallelWrapperSuperstep:
    def test_sharded_superstep_matches_per_batch(self, rng):
        from deeplearning4j_tpu.parallel import mesh as mesh_mod
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

        def conf(k):
            return (NeuralNetConfiguration.builder()
                    .seed(7).learning_rate(0.1).updater("sgd")
                    .weight_init("xavier").superstep_k(k)
                    .list()
                    .layer(DenseLayer(n_out=8, activation="tanh"))
                    .layer(OutputLayer(n_out=N_OUT, activation="softmax",
                                       loss_function="mcxent"))
                    .set_input_type(InputType.feed_forward(N_IN))
                    .build())

        batches = make_batches(rng, n_batches=5, batch=16)
        mesh = mesh_mod.create_mesh((8,), ("data",))

        ref = MultiLayerNetwork(conf(0)).init()
        ParallelWrapper(ref, mesh=mesh).fit(batches)

        net = MultiLayerNetwork(conf(2)).init()
        ParallelWrapper(net, mesh=mesh).fit(batches)

        assert_trees_identical(ref.params_tree, net.params_tree)
        assert ref.iteration == net.iteration == 5
        assert 2 in superstep_programs(net)

    def test_bench_lenet_superstep_smoke(self, monkeypatch):
        """Fast CPU pass of the BENCH config: both timed loops run, the
        superstep net actually compiles a fused program, and the emitted
        entries carry the same-run ratio."""
        import bench

        monkeypatch.setenv("BENCH_BATCH_LENET", "8")
        monkeypatch.setenv("BENCH_SUPERSTEP_K", "2")
        head, ratio = bench.bench_lenet_superstep(steps=4, warmup=1)
        assert head["metric"] == "lenet_superstep_k2_samples_per_sec"
        assert head["value"] > 0
        assert head["per_batch_same_run"] > 0
        assert ratio["metric"] == "lenet_superstep_vs_per_batch_ratio"
        assert ratio["value"] > 0

    def test_superbatch_sharding_spec(self):
        from jax.sharding import PartitionSpec as P

        from deeplearning4j_tpu.parallel import mesh as mesh_mod

        mesh = mesh_mod.create_mesh((8,), ("data",))
        s = mesh_mod.superbatch_sharding(mesh, ndim=3)
        assert s.spec == P(None, "data", None)
