"""Flight-recorder + HBM-attribution tests (the crash/NaN/preemption
forensics tier).

Three layers:
- FlightRecorder unit tests: ring semantics, bundle contents, per-reason
  rate limiting, the CLI pretty-printer;
- crash-forensics subprocess tests: a NaN-diverging fit and a SIGTERM'd
  run must each leave a self-contained bundle behind (MANIFEST + steps
  JSONL + a valid Chrome trace + metrics snapshot);
- memory attribution: per-program `memory_analysis()` gauges, live-buffer
  attribution to registered trees, and the serving host's measured-HBM
  eviction budgets.
"""

import json
import math
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.observability import memory as mem
from deeplearning4j_tpu.observability.flight import FlightRecorder
from deeplearning4j_tpu.observability.flight import main as flight_cli


def mlp_net():
    conf = (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.1).updater("sgd")
            .list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(4))
            .build())
    return MultiLayerNetwork(conf).init()


# The builder prelude shared by the subprocess children below.
_CHILD_PRELUDE = r"""
import numpy as np
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

conf = (NeuralNetConfiguration.builder()
        .seed(7).learning_rate(0.1).updater("sgd")
        .list()
        .layer(DenseLayer(n_out=8, activation="tanh"))
        .layer(OutputLayer(n_out=3, activation="softmax",
                           loss_function="mcxent"))
        .set_input_type(InputType.feed_forward(4))
        .build())
net = MultiLayerNetwork(conf).init()
rng = np.random.RandomState(0)
y = np.eye(3, dtype="float32")[rng.randint(0, 3, 8)]
"""


def _child_env(tmp_path, **extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DL4J_TPU_FLIGHT="1",
               DL4J_TPU_FLIGHT_DIR=str(tmp_path / "flight"))
    env.setdefault("DL4J_TPU_COMPILE_CACHE", str(tmp_path / "cache"))
    env.update(extra)
    return env


def _bundles(tmp_path):
    root = tmp_path / "flight"
    if not root.is_dir():
        return []
    return sorted(p for p in root.iterdir() if (p / "MANIFEST.json").exists())


def _assert_bundle_valid(bundle):
    """A bundle must be self-contained and parseable: manifest, steps
    JSONL, a structurally valid Chrome trace, and a metrics snapshot."""
    manifest = json.loads((bundle / "MANIFEST.json").read_text())
    assert manifest["bundle_format"] == 1
    assert manifest["pid"] > 0 and manifest["versions"]["python"]
    lines = (bundle / "steps.jsonl").read_text().splitlines()
    records = [json.loads(line) for line in lines if line.strip()]
    trace = json.loads((bundle / "trace.json").read_text())
    assert isinstance(trace["traceEvents"], list)
    for e in trace["traceEvents"]:
        assert e["ph"] in ("X", "i")
        assert "name" in e and "ts" in e
    metrics = json.loads((bundle / "metrics.json").read_text())
    assert isinstance(metrics, dict)
    return manifest, records, trace, metrics


# ----------------------------------------------------------- unit tests


class TestFlightRecorder:
    def test_ring_keeps_last_capacity_records(self, tmp_path):
        rec = FlightRecorder(capacity=16, enabled=True,
                             dump_dir=str(tmp_path))
        for i in range(40):
            rec.record_step("mln", i, loss=0.5, seconds=0.001)
        snap = rec.snapshot()
        assert len(snap) == 16
        assert [r["iteration"] for r in snap] == list(range(24, 40))
        seqs = [r["seq"] for r in snap]
        assert seqs == sorted(seqs)  # oldest first

    def test_disabled_recording_still_dumps_on_demand(self, tmp_path):
        rec = FlightRecorder(capacity=16, enabled=False,
                             dump_dir=str(tmp_path))
        rec.record_step("mln", 1, loss=0.5)
        rec.record_event("probe")
        assert rec.snapshot() == []
        bundle = rec.dump(reason="manual")
        assert bundle is not None and os.path.isfile(
            os.path.join(bundle, "MANIFEST.json"))

    def test_dump_bundle_contents_and_nan_loss_materialization(
            self, tmp_path):
        rec = FlightRecorder(capacity=32, enabled=True,
                             dump_dir=str(tmp_path))
        rec.record_step("mln", 1, loss=0.25, seconds=0.002, k=4,
                        h2d_bytes=1024, input_wait=0.0001,
                        jit_hits=1, jit_misses=1)
        rec.record_step("mln", 2, loss=float("nan"), seconds=0.002,
                        jit_hits=2, jit_misses=1)
        rec.record_event("nan_loss", engine="MultiLayerNetwork",
                         iteration=2)
        bundle = rec.dump(reason="nan-loss",
                          exc=FloatingPointError("non-finite loss"))
        from pathlib import Path

        manifest, records, trace, _metrics = _assert_bundle_valid(
            Path(bundle))
        assert manifest["reason"] == "nan-loss"
        assert manifest["exception"]["type"] == "FloatingPointError"
        steps = [r for r in records if r["type"] == "step"]
        assert [s["iteration"] for s in steps] == [1, 2]
        assert steps[0]["k"] == 4 and steps[0]["h2d_bytes"] == 1024
        assert steps[0]["input_wait"] == pytest.approx(0.0001)
        # the NaN loss must be JSON-safe (materialized to its repr)
        assert steps[1]["loss"] == "nan"
        assert steps[1]["jit_hits_delta"] == 1
        assert any(r["type"] == "nan_loss" for r in records)
        names = {e["name"] for e in trace["traceEvents"]}
        assert "mln.step" in names and "flight.nan_loss" in names

    def test_auto_dumps_are_rate_limited_per_reason(self, tmp_path,
                                                    monkeypatch):
        rec = FlightRecorder(capacity=16, enabled=True,
                             dump_dir=str(tmp_path))
        rec.min_interval_s = 3600.0
        first = rec.dump(reason="crash:mln.dispatch", force=False)
        assert first is not None
        assert rec.dump(reason="crash:mln.dispatch", force=False) is None
        # a different reason has its own limiter window
        assert rec.dump(reason="nan-loss", force=False) is not None
        # explicit dumps always write
        assert rec.dump(reason="crash:mln.dispatch", force=True) is not None

    def test_on_crash_records_event_and_dumps(self, tmp_path):
        rec = FlightRecorder(capacity=16, enabled=True,
                             dump_dir=str(tmp_path))
        bundle = rec.on_crash("serving.batch", ValueError("boom"))
        assert bundle is not None
        records = rec.snapshot()
        assert records and records[-1]["type"] == "crash"
        assert records[-1]["where"] == "serving.batch"
        manifest = json.loads(
            open(os.path.join(bundle, "MANIFEST.json")).read())
        assert manifest["reason"] == "crash:serving.batch"

    def test_status_shape(self, tmp_path):
        rec = FlightRecorder(capacity=16, enabled=True,
                             dump_dir=str(tmp_path))
        rec.record_step("mln", 1, loss=0.5)
        st = rec.status()
        assert st["enabled"] is True and st["capacity"] == 16
        assert st["records"] == 1 and st["dump_dir"] == str(tmp_path)
        assert st["dumps"] == [] and len(st["recent"]) == 1
        rec.clear()
        assert rec.status()["records"] == 0

    def test_cli_pretty_prints_bundle(self, tmp_path, capsys):
        rec = FlightRecorder(capacity=16, enabled=True,
                             dump_dir=str(tmp_path))
        for i in range(5):
            rec.record_step("mln", i, loss=0.5 - i * 0.01, seconds=0.001)
        bundle = rec.dump(reason="manual")
        assert flight_cli([bundle]) == 0
        out = capsys.readouterr().out
        assert "reason : manual" in out
        assert "5 step records" in out
        assert flight_cli([str(tmp_path / "nope")]) == 2

    def test_cli_module_is_runnable(self, tmp_path):
        """`observability.flight` the attribute is the recorder instance;
        the module must still be reachable for `python -m`."""
        rec = FlightRecorder(capacity=16, enabled=True,
                             dump_dir=str(tmp_path))
        rec.record_step("mln", 1, loss=0.5)
        bundle = rec.dump(reason="manual")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-m",
             "deeplearning4j_tpu.observability.flight", bundle],
            capture_output=True, text=True, env=env, timeout=300)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "flight bundle" in proc.stdout


# ------------------------------------------------- crash forensics (e2e)


_NAN_CHILD = _CHILD_PRELUDE + r"""
from deeplearning4j_tpu.analysis.runtime import install_nan_guard

install_nan_guard(net)
x = np.full((8, 4), np.nan, dtype="float32")
try:
    net.fit(DataSet(x, y))
except FloatingPointError:
    raise SystemExit(7)
raise SystemExit(3)  # the guard failed to fire
"""

_SIGTERM_CHILD = _CHILD_PRELUDE + r"""
import sys, time
x = rng.randn(8, 4).astype("float32")
for _ in range(3):
    net.fit(DataSet(x, y))  # records steps; installs the signal hooks
print("READY", flush=True)
time.sleep(120)
"""


class TestCrashForensics:
    def test_nan_loss_dumps_bundle_in_subprocess(self, tmp_path):
        """The headline scenario: a diverging run dies with
        FloatingPointError AND leaves a forensics bundle behind."""
        proc = subprocess.run([sys.executable, "-c", _NAN_CHILD],
                              capture_output=True, text=True,
                              env=_child_env(tmp_path), timeout=600)
        assert proc.returncode == 7, (proc.stdout, proc.stderr[-2000:])
        bundles = _bundles(tmp_path)
        assert len(bundles) == 1, "expected exactly one nan-loss bundle"
        manifest, records, trace, metrics = _assert_bundle_valid(bundles[0])
        assert manifest["reason"] == "nan-loss"
        assert "nan-loss" in bundles[0].name
        steps = [r for r in records if r["type"] == "step"]
        assert steps, "ring must hold the steps leading up to divergence"
        assert steps[-1]["loss"] == "nan"
        assert any(r["type"] == "nan_loss" for r in records)
        assert "dl4j_train_iterations_total" in metrics

    def test_sigterm_dumps_bundle_and_reraises(self, tmp_path):
        """Preemption forensics: SIGTERM writes a bundle, then the process
        still dies with the signal status (handlers chain/restore)."""
        proc = subprocess.Popen([sys.executable, "-c", _SIGTERM_CHILD],
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True,
                                env=_child_env(tmp_path))
        try:
            line = proc.stdout.readline()
            assert line.strip() == "READY", proc.stderr.read()[-2000:]
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=120)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        assert proc.returncode == -signal.SIGTERM
        bundles = _bundles(tmp_path)
        assert len(bundles) == 1
        manifest, records, _trace, _metrics = _assert_bundle_valid(
            bundles[0])
        assert manifest["reason"] == "signal:SIGTERM"
        assert any(r["type"] == "step" for r in records)


# ------------------------------------------------------ HBM attribution


class _FakeAnalysis:
    argument_size_in_bytes = 1000
    output_size_in_bytes = 200
    temp_size_in_bytes = 4096
    generated_code_size_in_bytes = 300
    alias_size_in_bytes = 96


class _FakeCompiled:
    def memory_analysis(self):
        return _FakeAnalysis()


class TestProgramMemory:
    def test_program_label(self):
        assert mem.program_label("train_step") == "train_step"
        assert (mem.program_label("solver_step", {"algo": "LBFGS", "k": 2})
                == "solver_step[algo=LBFGS,k=2]")

    def test_record_program_memory_sets_gauges(self):
        from deeplearning4j_tpu import observability as obs

        stats = mem.record_program_memory("test.fake_step", _FakeCompiled())
        assert stats == {"argument": 1000, "output": 200, "temp": 4096,
                         "generated_code": 300, "alias": 96,
                         "total": 1000 + 200 + 4096 + 300 - 96}
        snap = mem.program_memory_snapshot()
        assert snap["test.fake_step"]["temp"] == 4096
        fam = obs.metrics.get_family("dl4j_program_hbm_bytes")
        by_labels = {(c.labels["program"], c.labels["kind"]): c.get()
                     for c in fam.children()}
        assert by_labels[("test.fake_step", "temp")] == 4096
        assert by_labels[("test.fake_step", "total")] == 5500

    def test_record_program_memory_never_raises(self):
        class Broken:
            def memory_analysis(self):
                raise RuntimeError("backend says no")

        assert mem.record_program_memory("test.broken", Broken()) is None
        assert mem.record_program_memory("test.none", object()) is None
        assert "test.broken" not in mem.program_memory_snapshot()

    def test_real_train_step_records_memory(self, rng=None):
        """End to end on the CPU backend: fitting once must record the
        engine's train step in the per-program gauges."""
        from deeplearning4j_tpu.datasets.dataset import DataSet

        net = mlp_net()
        r = np.random.RandomState(0)
        x = r.randn(8, 4).astype("float32")
        y = np.eye(3, dtype="float32")[r.randint(0, 3, 8)]
        from deeplearning4j_tpu.observability import StepProfiler

        with StepProfiler(net):
            net.fit(DataSet(x, y))
        snap = mem.program_memory_snapshot()
        labels = [p for p in snap if "train_step" in p]
        assert labels, f"no train_step program recorded: {sorted(snap)}"
        assert all(snap[p]["total"] >= 0 for p in labels)


class TestLiveBufferAttribution:
    def test_registered_tree_owns_its_buffers(self):
        net = mlp_net()
        mem.register_tree("attr_probe", net)
        try:
            report = mem.live_buffer_report()
            assert report["total_bytes"] > 0
            assert "attr_probe" in report["models"]
            model = report["models"]["attr_probe"]
            assert model["bytes"] > 0
            assert any(g.startswith("params_tree/")
                       for g in model["groups"])
            assert (model["bytes"] + report["unattributed_bytes"]
                    <= report["total_bytes"] + 1)
        finally:
            mem.unregister_tree("attr_probe")
        report = mem.live_buffer_report()
        assert "attr_probe" not in report["models"]

    def test_measured_model_bytes(self):
        net = mlp_net()
        measured = mem.measured_model_bytes(net)
        assert measured is not None and measured > 0
        # at least the params themselves
        import jax

        params = sum(int(leaf.nbytes)
                     for leaf in jax.tree_util.tree_leaves(net.params_tree)
                     if isinstance(leaf, jax.Array))
        assert measured >= params

    def test_report_shape(self):
        doc = mem.report()
        assert set(doc) == {"programs", "live"}
        assert {"total_bytes", "models",
                "unattributed_bytes"} <= set(doc["live"])


class TestServingMeasuredHbm:
    def test_host_uses_measured_bytes_for_live_net(self):
        from deeplearning4j_tpu.serving.host import (
            ModelHost, estimate_hbm_bytes,
        )

        net = mlp_net()
        host = ModelHost()
        try:
            model = host.add("measured-probe", net=net)
            assert model.hbm_source == "measured"
            assert model.hbm_bytes >= estimate_hbm_bytes(net)
            rows = {r["name"]: r for r in host.snapshot()}
            row = rows["measured-probe"]
            assert row["hbm_source"] == "measured"
            assert row["hbm_bytes"] == model.hbm_bytes
            # the host registered the net for live attribution
            report = mem.live_buffer_report()
            assert "measured-probe" in report["models"]
        finally:
            host.stop()
            mem.unregister_tree("measured-probe")
