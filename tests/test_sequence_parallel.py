"""Sequence/context-parallel attention tests (8-device virtual CPU mesh).

The reference has no attention (SURVEY.md §5: tBPTT is its only
long-sequence mechanism); these tests cover the TPU-native extension —
exact equivalence of ring / Ulysses sequence-parallel attention against
dense single-device attention, values AND gradients, causal and full.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel import mesh as mesh_mod
from deeplearning4j_tpu.parallel.sequence import (
    dense_attention,
    ring_attention,
    ulysses_attention,
)


def qkv(rng, b=2, t=32, h=4, d=8, dtype="float64"):
    mk = lambda: rng.randn(b, t, h, d).astype(dtype)
    return jnp.asarray(mk()), jnp.asarray(mk()), jnp.asarray(mk())


@pytest.fixture(params=[(1, 8), (2, 4)], ids=["seq8", "data2xseq4"])
def mesh(request):
    dp, sp = request.param
    return mesh_mod.create_mesh((dp, sp), axis_names=("data", "seq"))


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False], ids=["causal", "full"])
    def test_matches_dense(self, rng, mesh, causal):
        q, k, v = qkv(rng)
        want = dense_attention(q, k, v, causal=causal)
        got = ring_attention(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-10, atol=1e-12)

    def test_grads_match_dense(self, rng, mesh):
        q, k, v = qkv(rng, t=16)
        w = jnp.asarray(rng.randn(*q.shape))

        def loss_ring(q, k, v):
            return jnp.sum(ring_attention(q, k, v, mesh, causal=True) * w)

        def loss_dense(q, k, v):
            return jnp.sum(dense_attention(q, k, v, causal=True) * w)

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for gr, gd in zip(g_ring, g_dense):
            np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                       rtol=1e-8, atol=1e-10)

    def test_jit_and_long_sequence(self, rng, mesh):
        # T=128 over 4-8 shards; jitted end-to-end.
        q, k, v = qkv(rng, b=2, t=128, h=2, d=4)
        f = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh, causal=True))
        np.testing.assert_allclose(
            np.asarray(f(q, k, v)),
            np.asarray(dense_attention(q, k, v, causal=True)),
            rtol=1e-10, atol=1e-12)

    def test_blockwise_never_materializes_full_scores(self, mesh):
        # Structural property: the jitted program's largest intermediate
        # stays O(T*T/p), not O(T^2). With T=64 on an 8-way seq axis the
        # per-device score block is [B, H, 8, 64]; assert no [.., 64, 64]
        # f32 buffer appears in the compiled HLO.
        if mesh.shape["seq"] != 8:
            pytest.skip("structural check on the seq8 mesh only")
        rng = np.random.RandomState(0)
        q, k, v = qkv(rng, b=1, t=64, h=1, d=4)
        f = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh, causal=True))
        hlo = f.lower(q, k, v).compile().as_text()
        assert "f32[1,1,64,64]" not in hlo


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [True, False], ids=["causal", "full"])
    def test_matches_dense(self, rng, mesh, causal):
        # n_heads must divide the seq axis: use h=8.
        q, k, v = qkv(rng, h=8)
        want = dense_attention(q, k, v, causal=causal)
        got = ulysses_attention(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-10, atol=1e-12)

    def test_rejects_indivisible_heads(self, rng, mesh):
        q, k, v = qkv(rng, h=3)
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, k, v, mesh)

    def test_grads_match_dense(self, rng, mesh):
        q, k, v = qkv(rng, t=16, h=8)
        w = jnp.asarray(rng.randn(*q.shape))
        g_u = jax.grad(lambda q, k, v: jnp.sum(
            ulysses_attention(q, k, v, mesh) * w), argnums=(0, 1, 2))(q, k, v)
        g_d = jax.grad(lambda q, k, v: jnp.sum(
            dense_attention(q, k, v) * w), argnums=(0, 1, 2))(q, k, v)
        for gu, gd in zip(g_u, g_d):
            np.testing.assert_allclose(np.asarray(gu), np.asarray(gd),
                                       rtol=1e-8, atol=1e-10)
