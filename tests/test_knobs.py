"""Tests for previously-dead configuration knobs (round-3 verdict weak #4/#5
and missing #7): DropConnect, per-param-type bias learning rate, and the VAE
Exponential/Composite reconstruction distributions.

Reference analogs: `LSTMHelpers.java:98-101` + `BaseLayer.preOutput:371-373`
(DropConnect), `FeedForwardLayer.getLearningRateByParam` /
`LayerUpdater.java:243` (biasLearningRate per param type),
`conf/layers/variational/` (reconstruction-distribution SPI).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    DenseLayer,
    GravesBidirectionalLSTM,
    OutputLayer,
    RnnOutputLayer,
    VariationalAutoencoder,
)
from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers.common import layer_input_dropout, maybe_drop_connect
from deeplearning4j_tpu.nn.layers.variational import dist_input_size, neg_log_prob
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


class TestDropConnect:
    def _conf(self, use_dc):
        return DenseLayer(n_in=8, n_out=4, dropout=0.5,
                          use_drop_connect=use_dc)

    def test_weights_are_dropped_at_train_time(self):
        conf = self._conf(True)
        W = jnp.ones((8, 4))
        rng = jax.random.PRNGKey(0)
        Wd = maybe_drop_connect(conf, W, rng, train=True)
        vals = np.unique(np.asarray(Wd))
        # Inverted scaling: surviving entries are 1/0.5 = 2, dropped are 0.
        assert set(vals.tolist()) <= {0.0, 2.0}
        assert 0.0 in vals and 2.0 in vals

    def test_inactive_paths(self):
        W = jnp.ones((8, 4))
        rng = jax.random.PRNGKey(0)
        # Inference: untouched.
        np.testing.assert_array_equal(
            maybe_drop_connect(self._conf(True), W, rng, train=False), W)
        # DropConnect off: untouched.
        np.testing.assert_array_equal(
            maybe_drop_connect(self._conf(False), W, rng, train=True), W)

    def test_input_dropout_skipped_in_dropconnect_mode(self):
        """Reference `applyDropOutIfNecessary:487` requires
        !isUseDropConnect — the two regularizers are mutually exclusive."""
        x = jnp.ones((3, 8))
        rng = jax.random.PRNGKey(1)
        np.testing.assert_array_equal(
            layer_input_dropout(self._conf(True), x, rng, train=True), x)
        dropped = layer_input_dropout(self._conf(False), x, rng, train=True)
        assert not np.allclose(np.asarray(dropped), np.asarray(x))

    def test_builder_flag_reaches_layers_and_training_runs(self, rng):
        conf = (NeuralNetConfiguration.builder()
                .seed(3).learning_rate(0.1).use_drop_connect(True)
                .drop_out(0.5)
                .list()
                .layer(DenseLayer(n_out=8, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss_function="mcxent"))
                .set_input_type(InputType.feed_forward(4))
                .build())
        assert conf.layers[0].use_drop_connect is True
        net = MultiLayerNetwork(conf).init()
        X = rng.randn(16, 4).astype("float32")
        Y = np.eye(3)[rng.randint(0, 3, 16)].astype("float32")
        s0 = net.score(DataSet(X, Y))
        for _ in range(20):
            net.fit(X, Y)
        assert net.score(DataSet(X, Y)) < s0
        # Inference is deterministic (no drop at test time).
        np.testing.assert_array_equal(net.output(X), net.output(X))


class TestBiasLearningRate:
    def test_bidirectional_lstm_biases_frozen_by_zero_bias_lr(self, rng):
        """bias_learning_rate must hit b_f/b_b (not just "b") — verdict
        weak #5; reference applies it per param TYPE."""
        conf = (NeuralNetConfiguration.builder()
                .seed(3).learning_rate(0.1).updater("sgd")
                .bias_learning_rate(0.0)
                .list()
                .layer(GravesBidirectionalLSTM(n_out=6, activation="tanh"))
                .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                      loss_function="mcxent"))
                .set_input_type(InputType.recurrent(3))
                .build())
        net = MultiLayerNetwork(conf).init()
        X = rng.randn(4, 5, 3).astype("float32")
        Y = np.zeros((4, 5, 2), "float32")
        Y[..., 0] = 1.0
        before = {k: np.asarray(v).copy()
                  for k, v in net.params_tree["layer_0"].items()}
        net.fit(DataSet(X, Y))
        after = net.params_tree["layer_0"]
        for bias in ("b_f", "b_b"):
            np.testing.assert_array_equal(before[bias], np.asarray(after[bias]))
        assert not np.allclose(before["W_f"], np.asarray(after["W_f"]))

    def test_doubled_bias_lr_scales_bias_update(self, rng):
        conf = (NeuralNetConfiguration.builder()
                .seed(3).learning_rate(0.1).updater("sgd")
                .bias_learning_rate(0.2)
                .list()
                .layer(OutputLayer(n_out=2, activation="softmax",
                                   loss_function="mcxent"))
                .set_input_type(InputType.feed_forward(4))
                .build())
        net = MultiLayerNetwork(conf).init()
        ref_conf = (NeuralNetConfiguration.builder()
                    .seed(3).learning_rate(0.1).updater("sgd")
                    .list()
                    .layer(OutputLayer(n_out=2, activation="softmax",
                                       loss_function="mcxent"))
                    .set_input_type(InputType.feed_forward(4))
                    .build())
        ref = MultiLayerNetwork(ref_conf).init()
        X = rng.randn(8, 4).astype("float32")
        Y = np.eye(2)[rng.randint(0, 2, 8)].astype("float32")
        b0 = np.asarray(net.params_tree["layer_0"]["b"]).copy()
        net.fit(DataSet(X, Y))
        ref.fit(DataSet(X, Y))
        db = np.asarray(net.params_tree["layer_0"]["b"]) - b0
        db_ref = np.asarray(ref.params_tree["layer_0"]["b"]) - b0
        np.testing.assert_allclose(db, 2.0 * db_ref, rtol=1e-5)


class TestVaeDistributions:
    def test_dist_input_sizes(self):
        assert dist_input_size("gaussian", 8) == 16
        assert dist_input_size("bernoulli", 8) == 8
        assert dist_input_size("exponential", 8) == 8
        assert dist_input_size([("gaussian", 5), ("bernoulli", 3)], 8) == 13
        with pytest.raises(ValueError):
            dist_input_size([("gaussian", 5)], 8)  # sizes must sum to 8
        with pytest.raises(ValueError):
            dist_input_size("cauchy", 8)

    def test_exponential_log_prob_formula(self):
        # log p(x) = gamma - lambda*x with lambda = exp(gamma).
        x = jnp.asarray([[2.0]])
        pre = jnp.asarray([[0.0]])  # lambda = 1
        np.testing.assert_allclose(
            np.asarray(neg_log_prob("exponential", x, pre)), [2.0])

    def test_composite_slices_match_parts(self):
        rng = np.random.RandomState(0)
        xg = jnp.asarray(rng.randn(4, 3))
        xb = jnp.asarray((rng.rand(4, 2) > 0.5).astype(float))
        pre_g = jnp.asarray(rng.randn(4, 6))
        pre_b = jnp.asarray(rng.randn(4, 2))
        whole = neg_log_prob([("gaussian", 3), ("bernoulli", 2)],
                             jnp.concatenate([xg, xb], axis=1),
                             jnp.concatenate([pre_g, pre_b], axis=1))
        parts = neg_log_prob("gaussian", xg, pre_g) + neg_log_prob(
            "bernoulli", xb, pre_b)
        np.testing.assert_allclose(np.asarray(whole), np.asarray(parts))

    @pytest.mark.parametrize("dist", [
        "exponential",
        [("gaussian", 4), ("bernoulli", 4)],
    ])
    def test_pretrain_improves_elbo(self, rng, dist):
        X = rng.rand(64, 8).astype("float64") + 0.1  # positive support
        conf = (NeuralNetConfiguration.builder().seed(5).learning_rate(0.01)
                .updater("adam")
                .list()
                .layer(VariationalAutoencoder(
                    n_out=4, encoder_layer_sizes=(16,),
                    decoder_layer_sizes=(16,), activation="tanh",
                    reconstruction_distribution=dist))
                .layer(OutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.feed_forward(8))
                .build())
        net = MultiLayerNetwork(conf).init()
        from deeplearning4j_tpu.nn.layers.variational import vae_pretrain_loss
        layer_conf = net.conf.layers[0]
        key = jax.random.PRNGKey(0)
        loss0 = float(vae_pretrain_loss(layer_conf,
                                        net.params_tree["layer_0"],
                                        jnp.asarray(X), key))
        net.pretrain(DataSet(X, np.zeros((64, 2), "float64")), epochs=30)
        loss1 = float(vae_pretrain_loss(layer_conf,
                                        net.params_tree["layer_0"],
                                        jnp.asarray(X), key))
        assert loss1 < loss0, (dist, loss0, loss1)
