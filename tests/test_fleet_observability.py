"""Cross-process observability plane (`observability/propagate.py` +
`observability/federation.py`).

Acceptance coverage for the observability-plane PR:

- trace-context propagation: W3C-style header mint/parse roundtrip,
  thread-local binding, remote-parent spans in the tracer;
- metrics federation: per-worker expositions merge under `worker_id`
  with valid family grouping; trace rings merge onto one wall-clock
  aligned Perfetto timeline;
- the coordinator exposes its own membership/lease/generation families
  and an HTTP `/metrics` advertised via `status.metrics_url`;
- the router's narrow load scrape (`?names=`) costs O(requested
  families) — its payload must not change as unrelated families are
  added, and scrape-time collectors must not run;
- `dl4j_build_info` identifies every process in a federated scrape;
- the real-fleet drill: a 3-process fleet (router in-proc + two replica
  subprocesses) under a hang fault produces ONE merged trace in which a
  single router request span parents replica spans from two DIFFERENT
  replica PIDs (the failover), and one federated scrape carries
  `dl4j_requests_total` from every replica worker_id.
"""

import json
import os
import socket
import threading
import time

import pytest

from deeplearning4j_tpu import (MultiLayerNetwork, NeuralNetConfiguration,
                                observability as obs)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.observability import federation as fed
from deeplearning4j_tpu.observability import propagate as prop
from deeplearning4j_tpu.observability.metrics import MetricsRegistry
from deeplearning4j_tpu.observability.tracing import Tracer
from deeplearning4j_tpu.parallel.coordinator import (
    Coordinator,
    CoordinatorClient,
)
from deeplearning4j_tpu.serving import FleetManager, FleetRouter


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def mlp_net(seed=1, n_in=3, n_out=2):
    return MultiLayerNetwork(
        (NeuralNetConfiguration.builder()
         .seed(seed).learning_rate(0.1).weight_init("xavier")
         .list()
         .layer(DenseLayer(n_out=4, activation="tanh"))
         .layer(OutputLayer(n_out=n_out, activation="softmax",
                            loss_function="mcxent"))
         .set_input_type(InputType.feed_forward(n_in))
         .build())).init()


def _save(net, path):
    from deeplearning4j_tpu.checkpoint.manager import CheckpointManager

    CheckpointManager(str(path), async_save=False).save(net)
    return str(path)


def _sub_env(plan=None):
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    if plan is not None:
        env["DL4J_TPU_FAULT_PLAN"] = json.dumps(plan)
    return env


def _wait(predicate, timeout_s, every_s=0.1, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(every_s)
    raise AssertionError(f"timed out after {timeout_s}s waiting for {what}")


# ------------------------------------------------------------ propagation


class TestTraceContext:
    def test_header_roundtrip(self):
        ctx = prop.mint()
        assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
        parsed = prop.parse(ctx.to_header())
        assert parsed == ctx

    def test_parse_rejects_garbage(self):
        assert prop.parse(None) is None
        assert prop.parse("") is None
        assert prop.parse("nonsense") is None
        assert prop.parse("00-xyz-abc-01") is None
        # all-zero ids are invalid per the W3C traceparent grammar
        assert prop.parse("00-" + "0" * 32 + "-" + "a" * 16 + "-01") is None
        assert prop.parse("00-" + "a" * 32 + "-" + "0" * 16 + "-01") is None

    def test_child_keeps_trace_id(self):
        ctx = prop.mint()
        child = ctx.child()
        assert child.trace_id == ctx.trace_id
        assert child.span_id != ctx.span_id

    def test_bound_installs_and_restores(self):
        assert prop.current() is None
        outer, inner = prop.mint(), prop.mint()
        with prop.bound(outer):
            assert prop.current() == outer
            with prop.bound(inner):
                assert prop.current() == inner
            assert prop.current() == outer
            with prop.bound(None):  # explicit clear for a block
                assert prop.current() is None
            assert prop.current() == outer
        assert prop.current() is None

    def test_trace_headers_reads_binding(self):
        assert prop.trace_headers() == {}
        ctx = prop.mint()
        with prop.bound(ctx):
            h = prop.trace_headers({"Content-Type": "application/json"})
            assert h[prop.TRACE_HEADER] == ctx.to_header()
            assert h["Content-Type"] == "application/json"

    def test_context_crosses_threads_via_explicit_capture(self):
        # The binding is thread-local: a worker thread sees None unless
        # the queue item carried the context (the batcher/scheduler
        # pattern).
        ctx = prop.mint()
        seen = {}

        def worker(captured):
            seen["current"] = prop.current()
            seen["captured"] = captured

        with prop.bound(ctx):
            t = threading.Thread(target=worker, args=(prop.current(),))
            t.start()
            t.join()
        assert seen["current"] is None
        assert seen["captured"] == ctx


class TestRemoteParentSpans:
    def test_span_ctx_fixes_identity(self):
        tr = Tracer(max_events=64)
        ctx = prop.mint()
        with tr.span("root", span_ctx=ctx):
            pass
        ev = tr.events()[-1]
        assert ev["args"]["trace_id"] == ctx.trace_id
        assert ev["args"]["span_id"] == ctx.span_id
        assert "parent_span_id" not in ev["args"]

    def test_parent_ctx_mints_child_under_remote_parent(self):
        tr = Tracer(max_events=64)
        remote = prop.mint()
        with tr.span("child", parent_ctx=remote) as sp:
            child_ctx = sp.ctx()
        ev = tr.events()[-1]
        assert ev["args"]["trace_id"] == remote.trace_id
        assert ev["args"]["parent_span_id"] == remote.span_id
        assert ev["args"]["span_id"] == child_ctx.span_id
        assert child_ctx.span_id != remote.span_id

    def test_nested_local_span_inherits_trace(self):
        tr = Tracer(max_events=64)
        ctx = prop.mint()
        with tr.span("outer", span_ctx=ctx):
            with tr.span("inner"):
                pass
        inner, outer = tr.events()[-2], tr.events()[-1]
        assert inner["name"] == "inner" and outer["name"] == "outer"
        assert inner["args"]["trace_id"] == ctx.trace_id
        assert inner["args"]["parent_span_id"] == ctx.span_id
        assert inner["args"]["parent"] == "outer"  # name back-compat

    def test_complete_records_retroactive_span(self):
        tr = Tracer(max_events=64)
        remote = prop.mint()
        t0 = time.perf_counter_ns()
        tr.complete("queue_wait", t0, 2_000_000, parent_ctx=remote,
                    model="m")
        ev = tr.events()[-1]
        assert ev["ph"] == "X"
        assert abs(ev["dur"] - 2000.0) < 1e-6  # 2ms in µs
        assert ev["args"]["trace_id"] == remote.trace_id
        assert ev["args"]["parent_span_id"] == remote.span_id

    def test_export_carries_merge_keys(self):
        tr = Tracer(max_events=64)
        doc = tr.export_chrome()
        assert doc["pid"] == os.getpid()
        # epoch anchor is wall-clock microseconds, sane magnitude
        assert doc["epochUnixUs"] > 1e15


# -------------------------------------------------------------- federation


class TestMergePrometheus:
    def test_worker_id_injected_and_families_grouped(self):
        merged = fed.merge_prometheus({
            "w1@h:1": ("# HELP dl4j_x total\n# TYPE dl4j_x counter\n"
                       'dl4j_x{route="a"} 3\ndl4j_x 1\n'),
            "w2@h:2": ("# TYPE dl4j_x counter\ndl4j_x{route=\"a\"} 5\n"
                       "# TYPE dl4j_y gauge\ndl4j_y 2\n"),
        })
        lines = merged.strip().splitlines()
        assert lines.count("# TYPE dl4j_x counter") == 1
        assert lines.count("# HELP dl4j_x total") == 1
        assert 'dl4j_x{worker_id="w1@h:1",route="a"} 3' in lines
        assert 'dl4j_x{worker_id="w1@h:1"} 1' in lines
        assert 'dl4j_x{worker_id="w2@h:2",route="a"} 5' in lines
        assert 'dl4j_y{worker_id="w2@h:2"} 2' in lines
        # exposition validity: all of a family's samples are contiguous
        # under its single TYPE line
        x_type = lines.index("# TYPE dl4j_x counter")
        y_type = lines.index("# TYPE dl4j_y gauge")
        x_samples = [i for i, l in enumerate(lines)
                     if l.startswith("dl4j_x")]
        assert all(x_type < i < y_type for i in x_samples)

    def test_histogram_suffixes_stay_in_family(self):
        reg = MetricsRegistry()
        reg.histogram("dl4j_t_seconds", "t", buckets=(0.1, 1.0)).observe(0.5)
        merged = fed.merge_prometheus({"w@h:1": reg.to_prometheus()})
        assert 'dl4j_t_seconds_bucket{worker_id="w@h:1",le="1"} 1' in merged
        assert 'dl4j_t_seconds_count{worker_id="w@h:1"} 1' in merged
        assert merged.count("# TYPE dl4j_t_seconds histogram") == 1


class TestMergeTraces:
    def test_timelines_align_on_epoch(self):
        docs = {
            "w1": {"traceEvents": [
                {"name": "a", "ph": "X", "ts": 5.0, "dur": 1.0,
                 "pid": 10, "tid": 1, "args": {}}],
                "epochUnixUs": 1000.0, "pid": 10},
            "w2": {"traceEvents": [
                {"name": "b", "ph": "X", "ts": 5.0, "dur": 1.0,
                 "pid": 20, "tid": 1, "args": {}}],
                "epochUnixUs": 1500.0, "pid": 20},
        }
        merged = fed.merge_traces(docs)
        evs = {e["name"]: e for e in merged["traceEvents"]
               if e.get("ph") == "X"}
        # earliest epoch is the shared zero; w2 shifts +500µs
        assert evs["a"]["ts"] == 5.0
        assert evs["b"]["ts"] == 505.0
        assert evs["a"]["args"]["worker_id"] == "w1"
        names = {(e["pid"], e["args"]["name"])
                 for e in merged["traceEvents"] if e.get("ph") == "M"}
        assert names == {(10, "w1"), (20, "w2")}

    def test_merged_doc_is_chrome_loadable(self):
        tr = Tracer(max_events=64)
        with tr.span("x"):
            pass
        merged = fed.merge_traces({"w": tr.export_chrome()})
        # loadable: serializes, and every event has the required keys
        body = json.loads(json.dumps(merged))
        assert body["traceEvents"]
        for ev in body["traceEvents"]:
            assert "name" in ev and "ph" in ev and "pid" in ev


# ----------------------------------------------- coordinator /metrics


class TestCoordinatorMetrics:
    def test_families_and_http_exposition(self):
        coord = Coordinator(lost_after_s=10.0).start()
        try:
            c1 = CoordinatorClient(coord.address, "t1@h:1", role="trainer")
            c2 = CoordinatorClient(coord.address, "r1@h:2", role="replica")
            c1.join()
            c2.join()
            c2.heartbeat()  # lease-age observation
            st = c1.status()
            assert st["metrics_url"] == coord.metrics_url
            import urllib.request

            text = urllib.request.urlopen(
                coord.metrics_url + "/metrics", timeout=2).read().decode()
            assert 'dl4j_coordinator_members{role="trainer"} 1' in text
            assert 'dl4j_coordinator_members{role="replica"} 1' in text
            assert "dl4j_coordinator_generation 2" in text
            assert "dl4j_coordinator_lease_age_seconds_count" in text
            # the narrow form works on the coordinator surface too
            narrow = urllib.request.urlopen(
                coord.metrics_url
                + "/metrics?names=dl4j_coordinator_generation",
                timeout=2).read().decode()
            assert narrow.strip().splitlines() == [
                "# HELP dl4j_coordinator_generation Current membership "
                "generation (bumps on every join/leave/eviction)",
                "# TYPE dl4j_coordinator_generation counter",
                "dl4j_coordinator_generation 2"]
            doc = json.loads(urllib.request.urlopen(
                coord.metrics_url + "/api/trace", timeout=2).read())
            assert "traceEvents" in doc
        finally:
            coord.close()

    def test_role_series_zeroes_when_member_leaves(self):
        coord = Coordinator(lost_after_s=10.0).start()
        try:
            c = CoordinatorClient(coord.address, "r1@h:2", role="replica")
            c.join()
            obs.metrics.to_prometheus()  # scrape: role seen
            c.leave()
            text = obs.metrics.to_prometheus()
            assert 'dl4j_coordinator_members{role="replica"} 0' in text
        finally:
            coord.close()


# ------------------------------------------------- narrow scrape cost


class TestNarrowScrapeRegression:
    def test_payload_does_not_scale_with_family_count(self):
        """The router's load poll must cost O(polled families): its
        payload is byte-identical before and after hundreds of unrelated
        families appear, and scrape-time collectors never run."""
        reg = MetricsRegistry()
        depth = reg.gauge("dl4j_serving_model_queue_depth", "q",
                          label_names=("model", "route"))
        busy = reg.gauge("dl4j_serving_decode_slots_busy", "b",
                         label_names=("model",))
        depth.labels(model="m", route="predict").set(3)
        busy.labels(model="m").set(2)
        names = ("dl4j_serving_model_queue_depth",
                 "dl4j_serving_decode_slots_busy")
        collector_ran = []
        reg.register_collector(lambda r: collector_ran.append(1))
        before = reg.to_prometheus(names=names)
        for i in range(300):
            fam = reg.counter(f"dl4j_noise_{i}_total", "n",
                              label_names=("k",))
            for j in range(3):
                fam.labels(k=str(j)).inc()
        after = reg.to_prometheus(names=names)
        assert after == before
        assert collector_ran == []  # narrow scrape skips collectors
        assert len(before.splitlines()) == 6  # 2 x (HELP, TYPE, sample)
        # the full scrape still sees everything (and runs collectors)
        full = reg.to_prometheus()
        assert "dl4j_noise_299_total" in full
        assert collector_ran == [1]

    def test_json_snapshot_narrowing_matches(self):
        reg = MetricsRegistry()
        reg.gauge("dl4j_serving_decode_slots_busy", "b",
                  label_names=("model",)).labels(model="m").set(4)
        reg.counter("dl4j_other_total", "o").inc()
        doc = reg.to_json(names=("dl4j_serving_decode_slots_busy",))
        assert set(doc) == {"dl4j_serving_decode_slots_busy"}
        assert doc["dl4j_serving_decode_slots_busy"]["series"][0][
            "value"] == 4.0

    def test_router_sums_json_snapshot(self):
        from deeplearning4j_tpu.serving.router import sum_metric_snapshot

        doc = {"dl4j_serving_model_queue_depth": {
                   "type": "gauge", "help": "",
                   "series": [{"labels": {"model": "a"}, "value": 2.0},
                              {"labels": {"model": "b"}, "value": 1.0}]},
               "dl4j_serving_decode_slots_busy": {
                   "type": "gauge", "help": "",
                   "series": [{"labels": {"model": "a"}, "value": 3.0}]},
               "dl4j_unrelated": {
                   "type": "counter", "help": "",
                   "series": [{"labels": {}, "value": 99.0}]}}
        got = sum_metric_snapshot(
            doc, ("dl4j_serving_model_queue_depth",
                  "dl4j_serving_decode_slots_busy"))
        assert got == 6.0


class TestBuildInfo:
    def test_build_info_in_exposition(self):
        text = obs.metrics.to_prometheus()
        line = next(l for l in text.splitlines()
                    if l.startswith("dl4j_build_info{"))
        assert 'version="' in line
        assert 'jax="' in line and 'backend="' in line
        assert 'device_kind="' in line
        assert line.endswith(" 1")


# --------------------------------------------------- the 3-process drill


class TestFleetTraceAndFederation:
    def test_failover_trace_and_federated_scrape(self, tmp_path):
        """One request's failover renders as ONE tree across processes:
        the router's root span parents `replica.predict` spans from two
        DIFFERENT replica PIDs (the hung replica records its span late —
        after the hang — so the merged view is polled). The federated
        scrape carries `dl4j_requests_total` from every replica."""
        ckpt = _save(mlp_net(seed=1), tmp_path / "ckpt")
        # Replica 0 hangs 2s at admission of its 3rd request; the
        # router's 0.75s attempt cap turns that into a failover onto
        # replica 1 while replica 0 SURVIVES (scrapeable afterwards).
        plan = [{"kind": "hang_replica", "step": 3, "worker": 0,
                 "seconds": 2.0}]
        coord = Coordinator(lost_after_s=5.0).start()
        manager = FleetManager(coord.address, ckpt, heartbeat_s=0.25,
                               env=_sub_env(plan),
                               log_dir=str(tmp_path / "logs"))
        manager.spawn()
        manager.spawn()
        router = FleetRouter(coord.address, poll_interval_s=0.1,
                             request_timeout_s=10.0,
                             attempt_timeout_s=0.75, quarantine_s=1.0,
                             http=False).start()
        try:
            _wait(lambda: sum(1 for r in router.table()
                              if r["state"] == "live") == 2,
                  120.0, what="2 live replicas")
            x = [[0.3, -0.1, 0.7]]
            for _ in range(12):
                router.predict(x, timeout_s=10.0)
                if router.counts()["failover"] >= 1:
                    break
            assert router.counts()["failover"] >= 1, router.counts()

            agg = router.aggregator()
            replica_wids = [r["worker_id"] for r in router.table()]
            assert len(replica_wids) == 2

            # ---- federated metrics: dl4j_requests_total from every wid
            text = agg.federate_metrics()
            for wid in replica_wids:
                assert f'worker_id="{wid}"' in text
                assert any(
                    l.startswith(f'dl4j_requests_total{{worker_id="{wid}"')
                    for l in text.splitlines()), wid
                assert f'dl4j_federation_up{{worker_id="{wid}"}} 1' in text
            # the coordinator's families federate too
            assert "dl4j_coordinator_members" in text
            # build identity from the replicas rides along
            assert any(l.startswith("dl4j_build_info{")
                       for l in text.splitlines())

            # ---- merged trace: one router span, two replica PIDs.
            # The hung replica records its span only after its 2s sleep,
            # so poll the merged view.
            found = {}

            def failover_tree_present():
                doc = agg.federate_trace()
                events = doc["traceEvents"]
                replica_spans = [e for e in events
                                 if e.get("name") == "replica.predict"
                                 and "parent_span_id" in e.get("args", {})]
                roots = {}
                for e in events:
                    a = e.get("args", {})
                    if (e.get("name") == "router.predict"
                            and "span_id" in a):
                        roots[a["span_id"]] = e
                for span_id, root in roots.items():
                    pids = {e["pid"] for e in replica_spans
                            if e["args"]["parent_span_id"] == span_id}
                    if len(pids) >= 2:
                        found["root"] = root
                        found["pids"] = pids
                        found["doc"] = doc
                        return True
                return False

            _wait(failover_tree_present, 20.0, every_s=0.5,
                  what="router span parenting 2 replica PIDs")
            assert len(found["pids"]) == 2
            # distinct OS processes, neither of them the router's
            assert os.getpid() not in found["pids"]
            # Perfetto-loadable: serializes; process_name metadata
            # labels both replica pids; every X event has ts+dur
            doc = json.loads(json.dumps(found["doc"]))
            meta_pids = {e["pid"] for e in doc["traceEvents"]
                         if e.get("ph") == "M"
                         and e.get("name") == "process_name"}
            assert found["pids"] <= meta_pids
            for e in doc["traceEvents"]:
                if e.get("ph") == "X":
                    assert "ts" in e and "dur" in e
            # the router-side attempt spans recorded the failover: at
            # least two attempts under one request
            attempts = [e for e in doc["traceEvents"]
                        if e.get("name") == "router.attempt"]
            assert len(attempts) >= 2
            # replica-side pipeline spans joined the same timeline
            names = {e.get("name") for e in doc["traceEvents"]}
            assert "serving.queue_wait" in names
            assert "serving.device_dispatch" in names
        finally:
            router.stop()
            manager.stop_all()
            coord.close()
