"""Checkpoint-format backward compatibility against COMMITTED golden
fixtures (reference analog: `regressiontest/RegressionTest050.java` et al. —
the reference commits serialized models from old versions and asserts they
still load and predict).

The fixtures in `tests/fixtures/` were written once (see the generation
recipe in the expect JSON's sibling commit) and must keep loading forever:
the zip format is load-bearing for failure recovery (`util/failure.py`
rolls back to the newest healthy checkpoint), so silent format drift would
break rollback of existing checkpoints in the field.
"""

import json
import os

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.util.checkpoint import load_checkpoint
from deeplearning4j_tpu.util.model_serializer import load_model

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def _golden_data():
    r = np.random.RandomState(77)
    X = r.randn(12, 5).astype("float32")
    Y = np.eye(3)[r.randint(0, 3, 12)].astype("float32")
    return X, Y


def _expect():
    with open(os.path.join(FIXTURES, "golden_expect_v1.json")) as f:
        return json.load(f)


def test_golden_model_zip_loads_and_predicts():
    exp = _expect()
    net = load_model(os.path.join(FIXTURES, "golden_model_v1.zip"))
    assert isinstance(net, MultiLayerNetwork)
    assert net.iteration == exp["iteration"]
    assert net.params().size == exp["params_sha_len"]
    np.testing.assert_allclose(net.params()[:16], exp["params_first16"],
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(net.updater_state_flat()[:16],
                               exp["updater_first16"], rtol=1e-6, atol=1e-7)
    X, _ = _golden_data()
    np.testing.assert_allclose(net.output(X), np.asarray(exp["output"]),
                               rtol=1e-5, atol=1e-6)


def test_golden_checkpoint_resumes_identically():
    """Load the committed checkpoint (params + updater + RNG continuation)
    and take one training step: the score must match the recorded value —
    the exact contract `util/failure.py` rollback depends on.

    Tolerance policy: the expect value is regenerated whenever an
    intentional numeric change lands in the traced train step, by running
    THIS test's exact recipe under the conftest environment (x64, 8 virtual
    CPU devices, hermetic `DL4J_TPU_COMPILE_CACHE`) and copying
    `net.score_value` into `score_after_resume_step`. The value must first
    prove device-count independent (identical under 1 and 8 devices) and
    eager/jit consistent to <1e-6; the assertion bound is then 1e-4 — f32
    params through one f32 step leave ~1e-7 jit-fusion slack, so 1e-4
    flags real semantic drift while ignoring instruction-ordering noise.
    Never regenerate against a warm user-level compile cache: a stale AOT
    entry replays an executable serialized from OLDER library code (the
    fingerprint hashes config/shapes/jax versions, not library code),
    which is how the previous expect value went bad."""
    exp = _expect()
    X, Y = _golden_data()
    net = load_checkpoint(os.path.join(FIXTURES, "golden_checkpoint_v1.zip"))
    net.fit(DataSet(X, Y))
    assert abs(float(net.score_value) - exp["score_after_resume_step"]) < 1e-4
