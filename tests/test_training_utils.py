"""Tests for listeners, early stopping, serialization, iterators, pretraining.

Reference analogs: `deeplearning4j-core/src/test/.../earlystopping/`,
`util/ModelSerializerTest`, `datasets/iterator/`, RBM/AE pretrain tests.
"""

import os

import numpy as np
import pytest

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets.builtin import (
    IrisDataSetIterator,
    MnistDataSetIterator,
    load_iris,
    load_mnist,
)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import (
    AsyncDataSetIterator,
    ExistingDataSetIterator,
    IteratorDataSetIterator,
    ListDataSetIterator,
    MultipleEpochsIterator,
    SamplingDataSetIterator,
)
from deeplearning4j_tpu.earlystopping import (
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingTrainer,
    InMemoryModelSaver,
    LocalFileModelSaver,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    AutoEncoder,
    DenseLayer,
    OutputLayer,
    RBM,
    VariationalAutoencoder,
)
from deeplearning4j_tpu.optimize.listeners import (
    CollectScoresIterationListener,
    PerformanceListener,
    ScoreIterationListener,
)
from deeplearning4j_tpu.util.model_serializer import load_model, save_model

from conftest import make_classification_data


def small_net(updater="adam", lr=0.05, seed=42):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(lr).updater(updater).weight_init("xavier")
            .list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(4)).build())
    return MultiLayerNetwork(conf).init()


class TestIterators:
    def test_list_iterator_batches(self, rng):
        X, Y = make_classification_data(rng, n=100)
        it = ListDataSetIterator(DataSet(X, Y), batch_size=32)
        sizes = [b.num_examples() for b in it]
        assert sizes == [32, 32, 32, 4]
        assert it.total_examples() == 100

    def test_list_iterator_shuffle_deterministic(self, rng):
        X, Y = make_classification_data(rng, n=20)
        it1 = ListDataSetIterator(DataSet(X, Y), batch_size=10, shuffle=True, seed=1)
        it2 = ListDataSetIterator(DataSet(X, Y), batch_size=10, shuffle=True, seed=1)
        np.testing.assert_array_equal(next(iter(it1)).features, next(iter(it2)).features)

    def test_async_iterator_same_data(self, rng):
        X, Y = make_classification_data(rng, n=64)
        base = ListDataSetIterator(DataSet(X, Y), batch_size=16)
        sync = [np.asarray(b.features) for b in base]
        got = [np.asarray(b.features) for b in AsyncDataSetIterator(base, device_prefetch=True)]
        assert len(got) == len(sync)
        for a, b in zip(sync, got):
            np.testing.assert_allclose(a, b)

    def test_async_iterator_propagates_errors(self):
        def bad():
            yield DataSet(np.zeros((2, 2)))
            raise RuntimeError("boom")

        with pytest.raises(RuntimeError, match="boom"):
            list(AsyncDataSetIterator(bad(), device_prefetch=False))

    def test_multiple_epochs(self, rng):
        X, Y = make_classification_data(rng, n=8)
        base = ListDataSetIterator(DataSet(X, Y), batch_size=8)
        assert len(list(MultipleEpochsIterator(3, base))) == 3

    def test_sampling_iterator(self, rng):
        X, Y = make_classification_data(rng, n=50)
        it = SamplingDataSetIterator(DataSet(X, Y), batch_size=16, total_batches=5, seed=0)
        batches = list(it)
        assert len(batches) == 5
        assert all(b.num_examples() == 16 for b in batches)

    def test_rebatching_iterator(self, rng):
        X, Y = make_classification_data(rng, n=30)
        stream = [DataSet(X[i:i + 7], Y[i:i + 7]) for i in range(0, 30, 7)]
        out = list(IteratorDataSetIterator(ExistingDataSetIterator(stream), batch_size=10))
        assert [b.num_examples() for b in out] == [10, 10, 10]

    def test_training_via_async(self, rng):
        X, Y = make_classification_data(rng)
        net = small_net()
        base = ListDataSetIterator(DataSet(X, Y), batch_size=16)
        for _ in range(30):
            net.fit(AsyncDataSetIterator(base))
        assert net.evaluate(DataSet(X, Y)).accuracy() > 0.85


class TestBuiltinDatasets:
    def test_mnist_shapes(self):
        ds = load_mnist(num_examples=256)
        assert ds.features.shape == (256, 28, 28, 1)
        assert ds.labels.shape == (256, 10)
        assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0

    def test_mnist_iterator_flat(self):
        it = MnistDataSetIterator(batch_size=64, num_examples=128, flat=True)
        b = next(iter(it))
        assert b.features.shape == (64, 784)

    def test_iris(self):
        ds = load_iris()
        assert ds.features.shape == (150, 4)
        assert ds.labels.sum() == 150

    def test_iris_learnable(self):
        net = small_net(lr=0.1)
        it = IrisDataSetIterator(batch_size=50)
        for _ in range(60):
            net.fit(it)
        ev = net.evaluate(IrisDataSetIterator())
        assert ev.accuracy() > 0.9


class TestListeners:
    def test_score_listener_fires(self, rng):
        X, Y = make_classification_data(rng)
        lines = []
        net = small_net().set_listeners(ScoreIterationListener(1, out=lines.append))
        net.fit(DataSet(X, Y))
        assert len(lines) == 1 and "Score at iteration" in lines[0]

    def test_collect_scores(self, rng):
        X, Y = make_classification_data(rng)
        col = CollectScoresIterationListener()
        net = small_net().set_listeners(col)
        for _ in range(5):
            net.fit(DataSet(X, Y))
        assert len(col.scores) == 5
        assert col.scores[-1][1] < col.scores[0][1]

    def test_performance_listener(self, rng):
        X, Y = make_classification_data(rng)
        msgs = []
        perf = PerformanceListener(frequency=2, out=msgs.append)
        net = small_net().set_listeners(perf)
        for _ in range(6):
            perf.record_batch(X.shape[0])
            net.fit(DataSet(X, Y))
        assert msgs and "batches/sec" in msgs[0]
        assert perf.last_samples_per_sec > 0


class TestModelSerializer:
    def test_roundtrip_multilayer(self, rng, tmp_path):
        X, Y = make_classification_data(rng)
        net = small_net()
        for _ in range(5):
            net.fit(DataSet(X, Y))
        path = tmp_path / "model.zip"
        save_model(net, path)
        net2 = load_model(path)
        np.testing.assert_allclose(net.params(), net2.params(), rtol=1e-7)
        np.testing.assert_allclose(net.updater_state_flat(), net2.updater_state_flat(), rtol=1e-7)
        np.testing.assert_allclose(net.output(X), net2.output(X), rtol=1e-5)
        assert net2.iteration == net.iteration
        # Continued training from a restore matches exactly: same rng seed path.
        assert abs(net2.score(DataSet(X, Y)) - net.score(DataSet(X, Y))) < 1e-8

    def test_roundtrip_graph(self, rng, tmp_path):
        from deeplearning4j_tpu import ComputationGraph
        X, Y = make_classification_data(rng)
        conf = (NeuralNetConfiguration.builder().seed(1).learning_rate(0.05)
                .updater("adam").graph_builder()
                .add_inputs("in")
                .add_layer("d", DenseLayer(n_in=4, n_out=8, activation="tanh"), "in")
                .add_layer("o", OutputLayer(n_in=8, n_out=3, activation="softmax"), "d")
                .set_outputs("o").build())
        net = ComputationGraph(conf).init()
        net.fit(X, Y)
        path = tmp_path / "graph.zip"
        save_model(net, path)
        net2 = load_model(path)
        np.testing.assert_allclose(net.params(), net2.params(), rtol=1e-7)
        np.testing.assert_allclose(net.output(X)[0], net2.output(X)[0], rtol=1e-5)


class TestEarlyStopping:
    def test_max_epochs(self, rng):
        X, Y = make_classification_data(rng)
        net = small_net()
        it = ListDataSetIterator(DataSet(X, Y), batch_size=32)
        cfg = (EarlyStoppingConfiguration.builder()
               .score_calculator(DataSetLossCalculator(DataSet(X, Y)))
               .model_saver(InMemoryModelSaver())
               .epoch_termination_conditions(MaxEpochsTerminationCondition(5))
               .build())
        result = EarlyStoppingTrainer(cfg, net, it).fit()
        assert result.total_epochs == 5
        assert result.termination_reason == "EpochTerminationCondition"
        assert result.best_model is not None
        assert result.best_model_score <= result.score_vs_epoch[0]

    def test_score_improvement_patience(self, rng):
        X, Y = make_classification_data(rng)
        net = small_net(lr=0.0)  # lr 0: no improvement ever
        it = ListDataSetIterator(DataSet(X, Y), batch_size=32)
        cfg = (EarlyStoppingConfiguration.builder()
               .score_calculator(DataSetLossCalculator(DataSet(X, Y)))
               .epoch_termination_conditions(
                   ScoreImprovementEpochTerminationCondition(2),
                   MaxEpochsTerminationCondition(50))
               .build())
        result = EarlyStoppingTrainer(cfg, net, it).fit()
        assert result.total_epochs <= 6
        assert result.termination_details == "ScoreImprovementEpochTerminationCondition"

    def test_max_score_guard(self, rng):
        X, Y = make_classification_data(rng)
        net = small_net(lr=1e4)  # diverges
        it = ListDataSetIterator(DataSet(X, Y), batch_size=32)
        cfg = (EarlyStoppingConfiguration.builder()
               .iteration_termination_conditions(MaxScoreIterationTerminationCondition(50.0))
               .epoch_termination_conditions(MaxEpochsTerminationCondition(20))
               .build())
        result = EarlyStoppingTrainer(cfg, net, it).fit()
        assert result.total_epochs < 20

    def test_local_file_saver(self, rng, tmp_path):
        X, Y = make_classification_data(rng)
        net = small_net()
        it = ListDataSetIterator(DataSet(X, Y), batch_size=32)
        cfg = (EarlyStoppingConfiguration.builder()
               .score_calculator(DataSetLossCalculator(DataSet(X, Y)))
               .model_saver(LocalFileModelSaver(str(tmp_path)))
               .epoch_termination_conditions(MaxEpochsTerminationCondition(3))
               .build())
        result = EarlyStoppingTrainer(cfg, net, it).fit()
        assert os.path.exists(tmp_path / "bestModel.zip")
        assert result.best_model is not None


class TestPretrain:
    def test_autoencoder_pretrain_reduces_reconstruction(self, rng):
        X = (rng.rand(64, 12) > 0.5).astype("float64")
        conf = (NeuralNetConfiguration.builder().seed(3).learning_rate(0.1)
                .updater("adam")
                .list()
                .layer(AutoEncoder(n_out=8, activation="sigmoid", corruption_level=0.2))
                .layer(OutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.feed_forward(12))
                .pretrain(True).backprop(False)
                .build())
        net = MultiLayerNetwork(conf).init()
        from deeplearning4j_tpu.nn.layers.feedforward import autoencoder_pretrain_loss
        import jax
        l0 = float(autoencoder_pretrain_loss(net.layers[0], net.params_tree["layer_0"],
                                             X, jax.random.PRNGKey(0)))
        net.pretrain(DataSet(X), epochs=40)
        l1 = float(autoencoder_pretrain_loss(net.layers[0], net.params_tree["layer_0"],
                                             X, jax.random.PRNGKey(0)))
        assert l1 < l0 * 0.9

    def test_rbm_pretrain_runs_and_improves_free_energy_gap(self, rng):
        X = (rng.rand(64, 10) > 0.5).astype("float64")
        conf = (NeuralNetConfiguration.builder().seed(4).learning_rate(0.05)
                .updater("sgd")
                .list()
                .layer(RBM(n_out=6, visible_unit="binary", hidden_unit="binary", k=1))
                .layer(OutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.feed_forward(10))
                .build())
        net = MultiLayerNetwork(conf).init()
        w0 = np.asarray(net.params_tree["layer_0"]["W"]).copy()
        net.pretrain(DataSet(X), epochs=10)
        w1 = np.asarray(net.params_tree["layer_0"]["W"])
        assert not np.allclose(w0, w1)
        assert np.isfinite(net.score_value)

    def test_vae_pretrain_elbo_improves(self, rng):
        X = rng.rand(64, 8).astype("float64")
        conf = (NeuralNetConfiguration.builder().seed(5).learning_rate(0.01)
                .updater("adam")
                .list()
                .layer(VariationalAutoencoder(
                    n_out=4, encoder_layer_sizes=(16,), decoder_layer_sizes=(16,),
                    activation="tanh", reconstruction_distribution="gaussian"))
                .layer(OutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.feed_forward(8))
                .build())
        net = MultiLayerNetwork(conf).init()
        from deeplearning4j_tpu.nn.layers.variational import vae_pretrain_loss
        import jax
        l0 = float(vae_pretrain_loss(net.layers[0], net.params_tree["layer_0"],
                                     X, jax.random.PRNGKey(0)))
        net.pretrain(DataSet(X), epochs=60)
        l1 = float(vae_pretrain_loss(net.layers[0], net.params_tree["layer_0"],
                                     X, jax.random.PRNGKey(0)))
        assert l1 < l0
