"""Multi-process distributed-training tests.

Reference analog: `dl4j-spark`'s `BaseSparkTest.java:90` local-cluster
pattern and `TestCompareParameterAveragingSparkVsSingleMachine.java` — the
key equivalence: distributed training must produce the same parameters as
single-machine training on the same data. Here two REAL OS processes join
a `jax.distributed` cluster, each feeding its half of every global batch
through `DistributedTrainer`; process 0 saves the final params, compared
against an in-process single-machine run.

Platform gate: `jax.distributed.initialize` succeeds everywhere, but
XLA:CPU rejects the first cross-process collective with "Multiprocess
computations aren't implemented on the CPU backend" — so on CPU-only
hosts these tests SKIP with that reason rather than hang/fail
(`dist.multiprocess_spmd_supported`). The coordinator-transport analog of
this equivalence runs everywhere in `tests/test_elastic.py`.

Worker bootstrap notes: virtual device fan-out comes from XLA_FLAGS
(`--xla_force_host_platform_device_count`) set in the worker env BEFORE
jax initializes its backend — `jax.config.update("jax_num_cpu_devices")`
does not exist in this jax and crashes the worker. Cluster join rides
`dist.initialize`'s built-in backoff retries (worker 1 may dial before
worker 0 binds), with a generous coordinator handshake timeout.
"""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import distributed as dist

pytestmark = pytest.mark.skipif(
    not dist.multiprocess_spmd_supported(),
    reason="XLA:CPU cannot run cross-process SPMD computations "
           "(jax.distributed joins, but the first collective fails with "
           "'Multiprocess computations aren't implemented on the CPU "
           "backend'); needs a TPU/GPU backend")

STEPS = 5
BATCH = 16  # global batch; each of 2 processes feeds 8 rows


def _conf_code():
    """The model/config/data, shared verbatim by the in-process single
    machine run and the worker script (same seeds => same init)."""
    return textwrap.dedent("""
        import numpy as np
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration

        def make_conf():
            return (NeuralNetConfiguration.builder()
                    .seed(7).learning_rate(0.1).updater("sgd")
                    .list()
                    .layer(DenseLayer(n_out=16, activation="tanh"))
                    .layer(OutputLayer(n_out=3, activation="softmax",
                                       loss_function="mcxent"))
                    .set_input_type(InputType.feed_forward(4))
                    .build())

        def make_data(step):
            r = np.random.RandomState(100 + step)
            X = r.randn(16, 4).astype("float32")
            Y = np.eye(3)[r.randint(0, 3, 16)].astype("float32")
            return X, Y
    """)


# Cluster join: dist.initialize retries the dial under backoff (worker
# startup order is unordered) and gives the coordinator handshake a
# generous window before giving up.
BOOTSTRAP = """
import os, sys
pid = int(sys.argv[1]); port = sys.argv[2]; out = sys.argv[3]
import jax
from deeplearning4j_tpu.parallel import distributed as dist
dist.initialize(coordinator_address="127.0.0.1:" + port,
                num_processes=2, process_id=pid,
                initialization_timeout=120)
"""


WORKER = BOOTSTRAP + """
assert dist.process_count() == 2 and jax.device_count() == 4

{conf_code}

import numpy as np
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

net = MultiLayerNetwork(make_conf()).init()
trainer = dist.DistributedTrainer(net)
for step in range({steps}):
    X, Y = make_data(step)
    lo, hi = pid * 8, (pid + 1) * 8   # this process's slice of the batch
    trainer.fit(DataSet(X[lo:hi], Y[lo:hi]))
if pid == 0:
    flat = {{f"{{k}}/{{p}}": np.asarray(v)
            for k, layer in net.params_tree.items()
            for p, v in layer.items()}}
    np.savez(out, **flat)
print("worker", pid, "done", flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_two_workers(tmp_path, script_text, devices_per_proc=2):
    port = _free_port()
    script = tmp_path / "worker.py"
    script.write_text(script_text)
    out = tmp_path / "params.npz"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    # Virtual CPU device fan-out must be in place before the worker's jax
    # backend initializes — env, not in-process config.
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devices_per_proc}")
    env["JAX_PLATFORMS"] = "cpu"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(pid), str(port), str(out)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env, text=True)
        for pid in (0, 1)]
    try:
        outputs = [p.communicate(timeout=300)[0] for p in procs]
        for p, text in zip(procs, outputs):
            assert p.returncode == 0, f"worker failed:\n{text[-3000:]}"
    finally:
        for p in procs:  # no orphaned workers stuck in a collective barrier
            if p.poll() is None:
                p.kill()
    return out, port


def test_two_process_training_matches_single_machine(tmp_path):
    script = WORKER.format(conf_code=_conf_code(), steps=STEPS)
    out, _ = _run_two_workers(tmp_path, script)

    # Single-machine run on the SAME data stream.
    ns = {}
    exec(_conf_code(), ns)
    net = MultiLayerNetwork(ns["make_conf"]()).init()
    for step in range(STEPS):
        X, Y = ns["make_data"](step)
        net.fit(DataSet(X, Y))

    got = np.load(str(out))
    for lk, layer in net.params_tree.items():
        for pk, v in layer.items():
            np.testing.assert_allclose(
                got[f"{lk}/{pk}"], np.asarray(v), rtol=2e-5, atol=2e-6,
                err_msg=f"param {lk}/{pk} diverged from single-machine run")


GRAPH_CONF = textwrap.dedent("""
    import numpy as np
    from deeplearning4j_tpu.nn.conf.graph import MergeVertex
    from deeplearning4j_tpu.nn.conf.inputs import InputType
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.conf.neural_net import NeuralNetConfiguration

    def make_conf():
        gb = (NeuralNetConfiguration.builder()
              .seed(9).learning_rate(0.1).updater("sgd")
              .graph_builder()
              .add_inputs("in")
              .add_layer("a", DenseLayer(n_out=12, activation="tanh"), "in")
              .add_layer("b", DenseLayer(n_out=12, activation="relu"), "in")
              .add_vertex("m", MergeVertex(), "a", "b")
              .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                            loss_function="mcxent"), "m")
              .set_outputs("out"))
        gb.set_input_types(InputType.feed_forward(4))
        return gb.build()

    def make_data(step):
        r = np.random.RandomState(200 + step)
        X = r.randn(16, 4).astype("float32")
        Y = np.eye(3)[r.randint(0, 3, 16)].astype("float32")
        return X, Y
""")


GRAPH_WORKER = BOOTSTRAP + """
assert dist.process_count() == 2 and jax.device_count() == 4

{conf_code}

import numpy as np
from deeplearning4j_tpu.datasets.dataset import MultiDataSet
from deeplearning4j_tpu.nn.graph import ComputationGraph

net = ComputationGraph(make_conf()).init()
{mesh_code}
for step in range({steps}):
    X, Y = make_data(step)
    lo, hi = pid * 8, (pid + 1) * 8
    trainer.fit(MultiDataSet(features=[X[lo:hi]], labels=[Y[lo:hi]]))
if pid == 0:
    flat = {{f"{{k}}/{{p}}": np.asarray(v)
            for k, layer in net.params_tree.items()
            for p, v in layer.items()}}
    np.savez(out, **flat)
print("worker", pid, "done", flush=True)
"""

MLN_TP_WORKER = BOOTSTRAP + """
assert dist.process_count() == 2 and jax.device_count() == 4

{conf_code}

import numpy as np
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

net = MultiLayerNetwork(make_conf()).init()
# dp x tp global mesh: 2 data x 2 model over 4 devices / 2 processes.
mesh = dist.global_mesh((2, 2), axis_names=("data", "model"))
trainer = dist.DistributedTrainer(net, mesh=mesh, model_axis="model")
for step in range({steps}):
    X, Y = make_data(step)
    lo, hi = pid * 8, (pid + 1) * 8
    trainer.fit(DataSet(X[lo:hi], Y[lo:hi]))
if pid == 0:
    flat = {{f"{{k}}/{{p}}": np.asarray(v)
            for k, layer in net.params_tree.items()
            for p, v in layer.items()}}
    np.savez(out, **flat)
print("worker", pid, "done", flush=True)
"""


def test_two_process_graph_training_matches_single_machine(tmp_path):
    """ComputationGraph (branch + merge topology) across 2 real processes
    equals the single-machine run (round-5 multi-host hardening)."""
    from deeplearning4j_tpu.datasets.dataset import MultiDataSet
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    script = GRAPH_WORKER.format(
        conf_code=GRAPH_CONF, steps=STEPS,
        mesh_code="trainer = dist.DistributedTrainer(net)")
    out, _ = _run_two_workers(tmp_path, script)

    ns = {}
    exec(GRAPH_CONF, ns)
    cg = ComputationGraph(ns["make_conf"]()).init()
    for step in range(STEPS):
        X, Y = ns["make_data"](step)
        cg.fit(MultiDataSet(features=[X], labels=[Y]))

    got = np.load(str(out))
    for lk, layer in cg.params_tree.items():
        for pk, v in layer.items():
            np.testing.assert_allclose(
                got[f"{lk}/{pk}"], np.asarray(v), rtol=2e-5, atol=2e-6,
                err_msg=f"graph param {lk}/{pk} diverged")


def test_two_process_dp_tp_mesh_matches_single_machine(tmp_path):
    """2-process dp(2) x tp(2) mesh: tensor-parallel weight sharding
    composed with cross-host data parallelism still reproduces the
    single-machine parameters."""
    script = MLN_TP_WORKER.format(conf_code=_conf_code(), steps=STEPS)
    out, _ = _run_two_workers(tmp_path, script)

    ns = {}
    exec(_conf_code(), ns)
    net = MultiLayerNetwork(ns["make_conf"]()).init()
    for step in range(STEPS):
        X, Y = ns["make_data"](step)
        net.fit(DataSet(X, Y))

    got = np.load(str(out))
    for lk, layer in net.params_tree.items():
        for pk, v in layer.items():
            np.testing.assert_allclose(
                got[f"{lk}/{pk}"], np.asarray(v), rtol=2e-5, atol=2e-6,
                err_msg=f"param {lk}/{pk} diverged (dp x tp)")


CORPUS_WORKER = BOOTSTRAP + """
assert dist.process_count() == 2

import numpy as np
from deeplearning4j_tpu.nlp.distributed_corpus import (
    distributed_cooccurrences, distributed_vocab,
)

# Each process holds HALF the corpus; the pipeline must produce the
# single-machine global result on every process.
corpus = [[f"w{j}" for j in np.random.RandomState(s).randint(0, 20, 12)]
          for s in range(8)]
shard = corpus[pid * 4:(pid + 1) * 4]
vocab, seqs = distributed_vocab(shard, min_word_frequency=2)
r, c, v = distributed_cooccurrences(seqs, window_size=3)
if pid == 0:
    np.savez(out,
             words=np.array(vocab.words()),
             freqs=np.array([w.frequency for w in vocab._by_index]),
             rows=r, cols=c, vals=v,
             seq0=seqs[0])
print("worker", pid, "done", flush=True)
"""


def test_two_process_corpus_pipeline_matches_single_machine(tmp_path):
    """TextPipeline analog: per-process shard counting merged over the
    collective fabric equals single-machine counting of the full corpus."""
    from deeplearning4j_tpu.nlp.glove import CoOccurrences
    from deeplearning4j_tpu.nlp.tokenization import (
        TokenizerFactory, tokenize_corpus,
    )
    from deeplearning4j_tpu.nlp.vocab import VocabConstructor

    out, _ = _run_two_workers(tmp_path, CORPUS_WORKER)
    got = np.load(str(out))

    corpus = [[f"w{j}" for j in np.random.RandomState(s).randint(0, 20, 12)]
              for s in range(8)]
    ref = VocabConstructor(2).build(
        tokenize_corpus(corpus, TokenizerFactory()))
    assert got["words"].tolist() == ref.words()
    np.testing.assert_allclose(
        got["freqs"], [w.frequency for w in ref._by_index])
    # Worker 0's first sentence encoded against the GLOBAL vocab.
    want0 = [ref.index_of(t) for t in corpus[0] if ref.contains_word(t)]
    assert got["seq0"].tolist() == want0
    # Cooccurrences: encode the whole corpus, count single-machine, compare.
    seqs_all = [np.asarray([ref.index_of(t) for t in s
                            if ref.contains_word(t)], np.int32)
                for s in corpus]
    rr, cc, vv = CoOccurrences(3, True).count(seqs_all)
    want = {(int(a), int(b)): float(w) for a, b, w in zip(rr, cc, vv)}
    got_d = {(int(a), int(b)): float(w)
             for a, b, w in zip(got["rows"], got["cols"], got["vals"])}
    assert got_d.keys() == want.keys()
    for k in want:
        assert abs(got_d[k] - want[k]) < 1e-5
