"""Request-lifecycle ledger, per-tenant cost attribution, and the SLO
burn-rate engine (ISSUE 17).

Acceptance coverage:

- the ledger itself: bounded ring, per-tenant rollups, NOOP when
  disabled, deterministic JSONL spool sampling, tenant counters;
- serving integration: predict AND generate populate records, attributed
  device-seconds reconcile with the unsplit
  `dl4j_serving_dispatch_seconds_total` choke-point counter within 5%
  on a two-adapter server, `GET /v1/tenants` carries the accounting
  rows with adapter HBM share, `dl4j_adapter_requests_total` carries
  the outcome label;
- `POST /admin/flight-dump` freezes one bundle (with `ledger.jsonl`)
  and rate-limits repeats per reason;
- the burn-rate engine: exposition parsing, exact bucket-ladder bad
  counts, multi-window page/recovery transitions, one `on_page` per
  sustained breach, counter-reset clamping;
- federation staleness: a lease-expired member is dropped from the
  scrape set and surfaced as `dl4j_federation_up 0` within one poll;
- the benchdiff sentinel: committed BENCH_out.json vs BASELINE.json
  gates clean; synthetic regressions exit non-zero with direction and
  per-metric tolerance honored;
- the fleet drill: 3 in-process replicas x 2 LoRA tenants under mixed
  traffic — federated `/v1/tenants` device-seconds reconcile with
  dispatch seconds, and a chaos latency breach pages at `/fleet/slo`
  producing EXACTLY ONE flight bundle across the fleet.
"""

import json
import os
import urllib.error
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import (MultiLayerNetwork, NeuralNetConfiguration,
                                observability as obs)
from deeplearning4j_tpu.nn import lora as lora_mod
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.transfer import TransferLearning
from deeplearning4j_tpu.observability import federation as fed
from deeplearning4j_tpu.observability import slo as slo_mod
from deeplearning4j_tpu.observability.ledger import (NOOP_RECORD,
                                                     RequestLedger)
from deeplearning4j_tpu.serving import InferenceServer


def mlp_net(seed=1, n_in=3, n_out=2):
    return MultiLayerNetwork(
        (NeuralNetConfiguration.builder()
         .seed(seed).learning_rate(0.1).weight_init("xavier")
         .list()
         .layer(DenseLayer(n_out=4, activation="tanh"))
         .layer(OutputLayer(n_out=n_out, activation="softmax",
                            loss_function="mcxent"))
         .set_input_type(InputType.feed_forward(n_in))
         .build())).init()


def _counter_total(name, **match):
    fam = obs.metrics.get_family(name)
    if fam is None:
        return 0.0
    return sum(c.get() for c in fam.children()
               if all(c.labels.get(k) == v for k, v in match.items()))


def _post(url, route, payload, timeout=60):
    req = urllib.request.Request(url + route, json.dumps(payload).encode(),
                                 {"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get(url, route, timeout=30):
    with urllib.request.urlopen(url + route, timeout=timeout) as r:
        return json.loads(r.read())


def _tenant_net(base, seed):
    """Deterministic distinct tenant (see test_transfer_lora): public
    TransferLearning path, seeded adapter factors."""
    tuned = TransferLearning(base).add_lora(rank=2, alpha=4).build()
    rng = np.random.RandomState(seed)
    for lk, lp in tuned.params_tree.items():
        for name in list(lp if isinstance(lp, dict) else ()):
            if name.endswith((lora_mod.LORA_A, lora_mod.LORA_B)):
                lp[name] = jnp.asarray(
                    rng.normal(0.0, 0.5, lp[name].shape).astype(np.float32))
    return tuned


@pytest.fixture(scope="module")
def lm():
    from deeplearning4j_tpu.models import zoo
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    conf = zoo.transformer_lm(vocab_size=17, t=16, d_model=16, n_heads=2,
                              n_blocks=1, decode_cache_length=32)
    return ComputationGraph(conf).init()


# ------------------------------------------------------------ ledger unit


class TestRequestLedger:
    def test_open_close_ring_and_tenant_rollup(self):
        led = RequestLedger(capacity=64, enabled=True, spool_path="",
                            sample=1.0)
        rec = led.open(route="predict", model="ledgerunit_m1",
                       adapter="t1", tokens_in=3)
        rec.mark("admitted")
        rec.add_device_seconds(0.25)
        rec.add_device_seconds(0.25)
        rec.add_tokens_out(2)
        rec.set_queue_wait(0.1)
        rec.set_prefix_hit(True)
        rec.add_speculative(accepted=4, rejected=1)
        rec.add_cow_copies(2)
        led.close(rec, outcome="ok")

        docs = led.snapshot()
        assert len(docs) == 1
        doc = docs[0]
        assert doc["route"] == "predict"
        assert doc["model"] == "ledgerunit_m1"
        assert doc["adapter"] == "t1"
        assert doc["tokens_in"] == 3 and doc["tokens_out"] == 2
        assert doc["device_seconds"] == pytest.approx(0.5)
        assert doc["spec_accepted"] == 4 and doc["spec_rejected"] == 1
        assert doc["cow_page_copies"] == 2
        assert doc["prefix_hit"] is True
        assert doc["outcome"] == "ok" and doc["duration_s"] >= 0
        assert "admitted" in doc["marks"]

        (row,) = led.tenants()
        assert (row["model"], row["adapter"]) == ("ledgerunit_m1", "t1")
        assert row["requests"] == 1
        assert row["device_seconds"] == pytest.approx(0.5)
        assert row["outcomes"] == {"ok": 1}
        assert row["queue_wait_mean_s"] == pytest.approx(0.1)

    def test_disabled_ledger_hands_out_noop(self):
        led = RequestLedger(capacity=64, enabled=False)
        rec = led.open(route="predict", model="m")
        assert rec is NOOP_RECORD
        rec.mark("x")
        rec.add_device_seconds(1.0)  # all no-ops, never raise
        led.close(rec)
        assert led.snapshot() == [] and led.tenants() == []
        assert led.status()["enabled"] is False

    def test_ring_is_bounded(self):
        led = RequestLedger(capacity=16, enabled=True, spool_path="",
                            sample=1.0)
        for i in range(40):
            led.close(led.open(route="predict", model="ledgerunit_ring"))
        st = led.status()
        assert len(led.snapshot()) == 16
        assert st["closed_total"] == 40
        # tenant aggregates keep counting past the ring horizon
        (row,) = led.tenants()
        assert row["requests"] == 40

    def test_spool_sampling_is_deterministic(self, tmp_path):
        spool = str(tmp_path / "led" / "spool.jsonl")
        led = RequestLedger(capacity=64, enabled=True, spool_path=spool,
                            sample=0.5)  # every 2nd closed record
        for i in range(6):
            rec = led.open(route="generate", model="m", tokens_in=i)
            led.close(rec, outcome="ok")
        lines = [json.loads(x) for x in
                 open(spool).read().splitlines()]
        assert len(lines) == 3
        assert all(doc["route"] == "generate" for doc in lines)

    def test_tenant_counters_roll_up(self):
        d0 = _counter_total("dl4j_tenant_device_seconds_total",
                            model="ledgerunit_ctr", adapter="a")
        t0 = _counter_total("dl4j_tenant_tokens_total",
                            model="ledgerunit_ctr", adapter="a")
        led = RequestLedger(capacity=16, enabled=True, spool_path="",
                            sample=1.0)
        rec = led.open(route="generate", model="ledgerunit_ctr",
                       adapter="a", tokens_in=7)
        rec.add_device_seconds(0.125)
        rec.add_tokens_out(5)
        led.close(rec, outcome="ok")
        assert _counter_total("dl4j_tenant_device_seconds_total",
                              model="ledgerunit_ctr",
                              adapter="a") - d0 == pytest.approx(0.125)
        assert _counter_total("dl4j_tenant_tokens_total",
                              model="ledgerunit_ctr",
                              adapter="a") - t0 == 12  # 7 in + 5 out


# ----------------------------------------------- serving-tier integration


class TestServerLedgerIntegration:
    def test_two_tenants_reconcile_and_v1_tenants(self, lm):
        server = InferenceServer(lm, warmup=True, max_batch_size=4,
                                 decode_slots=2, kv_cache="paged",
                                 kv_page_size=8)
        server.load_adapter("tenant-a", net=_tenant_net(lm, 1))
        server.load_adapter("tenant-b", net=_tenant_net(lm, 2))
        server.start()
        try:
            assert server.wait_ready(600)
            obs.request_ledger.clear()
            d0 = _counter_total("dl4j_serving_dispatch_seconds_total",
                                model="default")
            a0 = _counter_total("dl4j_adapter_requests_total",
                                model="default", adapter="tenant-a",
                                outcome="ok")

            x = np.asarray([[[t % 7] for t in range(16)]], np.int32)
            for adapter in (None, "tenant-a", "tenant-b"):
                server.predict(x, adapter=adapter)
                server.generate([1, 2, 3], 5, temperature=0.0,
                                adapter=adapter)

            # Attributed device-seconds reconcile with the UNSPLIT
            # dispatch wall-time counter at the choke points: the split
            # must conserve time, not approximate it.
            delta = _counter_total("dl4j_serving_dispatch_seconds_total",
                                   model="default") - d0
            rows = server.tenant_snapshot()
            total = sum(r["device_seconds"] for r in rows)
            assert delta > 0
            assert abs(total - delta) <= 0.05 * delta

            by_adapter = {r["adapter"]: r for r in rows}
            assert set(by_adapter) == {"", "tenant-a", "tenant-b"}
            for name in ("tenant-a", "tenant-b"):
                row = by_adapter[name]
                assert row["requests"] == 2  # one predict + one generate
                assert row["tokens_in"] > 0 and row["tokens_out"] == 5
                assert row["outcomes"] == {"ok": 2}
                assert row["hbm_bytes"] > 0
                assert 0.0 < row["hbm_share"] < 1.0
            assert by_adapter[""]["hbm_bytes"] is None

            # The generate record carries the lifecycle marks.
            gen_docs = [d for d in obs.request_ledger.snapshot()
                        if d["route"] == "generate"]
            assert gen_docs
            assert {"admitted", "first_token"} <= set(gen_docs[-1]["marks"])
            assert gen_docs[-1]["prefix_hit"] in (True, False)

            # Same rows over HTTP.
            http_rows = _get(server.url, "/v1/tenants")["tenants"]
            assert {(r["model"], r["adapter"]) for r in http_rows} == {
                ("default", ""), ("default", "tenant-a"),
                ("default", "tenant-b")}

            # Satellite: the adapter counter now carries `outcome`.
            assert _counter_total("dl4j_adapter_requests_total",
                                  model="default", adapter="tenant-a",
                                  outcome="ok") - a0 == 2
        finally:
            server.stop()

    def test_failed_request_lands_with_outcome(self, lm):
        from deeplearning4j_tpu.serving.errors import InputValidationError

        server = InferenceServer(lm, decode_slots=2)
        server.load_adapter("t", net=_tenant_net(lm, 3))
        try:
            obs.request_ledger.clear()
            with pytest.raises(InputValidationError):
                server.generate([1, 2], 2, adapter="nope")
            docs = obs.request_ledger.snapshot()
            assert docs and docs[-1]["outcome"] == "invalid"
            f0 = _counter_total("dl4j_adapter_requests_total",
                                model="default", adapter="nope",
                                outcome="failed")
            assert f0 >= 1  # invalid folds into the bounded outcome enum
        finally:
            server.stop()

    def test_flight_dump_route_rate_limited_with_ledger(self, lm, tmp_path,
                                                        monkeypatch):
        monkeypatch.setattr(obs.flight, "dump_dir", str(tmp_path))
        server = InferenceServer(lm, decode_slots=2)
        server.start()
        try:
            assert server.wait_ready(600)
            server.generate([1, 2, 3], 3, temperature=0.0)
            reason = "testdump-ledger-route"
            doc = _post(server.url, "/admin/flight-dump",
                        {"reason": reason})
            assert doc["path"] is not None
            bundle = doc["path"]
            assert os.path.isfile(os.path.join(bundle, "ledger.jsonl"))
            recs = [json.loads(x) for x in
                    open(os.path.join(bundle, "ledger.jsonl"))
                    .read().splitlines()]
            assert any(r["route"] == "generate" for r in recs)
            # Same reason again inside the min interval: rate-limited.
            doc2 = _post(server.url, "/admin/flight-dump",
                         {"reason": reason})
            assert doc2["path"] is None
        finally:
            server.stop()


# ------------------------------------------------------- burn-rate engine


def _expo(wid, ok, err, ttft=None):
    """One worker's federated sample lines (cumulative counters)."""
    lines = [
        f'dl4j_requests_total{{worker_id="{wid}",model="m",'
        f'route="generate",outcome="ok"}} {ok}',
        f'dl4j_requests_total{{worker_id="{wid}",model="m",'
        f'route="generate",outcome="error"}} {err}',
    ]
    if ttft is not None:
        under, total = ttft
        lines += [
            f'dl4j_serving_ttft_seconds_bucket{{worker_id="{wid}",'
            f'model="m",le="1.0"}} {under}',
            f'dl4j_serving_ttft_seconds_bucket{{worker_id="{wid}",'
            f'model="m",le="+Inf"}} {total}',
        ]
    return "\n".join(lines) + "\n"


class TestBurnRateEngine:
    def test_parse_prometheus_splits_workers(self):
        text = _expo("w1", 3, 1) + _expo("w2", 5, 0) + \
            "# HELP dl4j_requests_total x\nnot a sample\n" + \
            "dl4j_plain_total 7\n"
        parsed = slo_mod.parse_prometheus(text)
        assert set(parsed) == {"w1", "w2", ""}
        names = {n for n, _, _ in parsed["w1"]}
        assert names == {"dl4j_requests_total"}
        # worker_id is stripped from the sample labels
        _, labels, value = [s for s in parsed["w2"]
                            if s[2] == 5.0][0]
        assert "worker_id" not in labels

    def test_latency_bucket_ladder_is_exact(self):
        o = slo_mod.Objective("ttft", "latency",
                              "dl4j_serving_ttft_seconds",
                              target=0.99, threshold_s=1.0)
        samples = [
            ("dl4j_serving_ttft_seconds_bucket", {"le": "0.5"}, 90.0),
            ("dl4j_serving_ttft_seconds_bucket", {"le": "1.0"}, 95.0),
            ("dl4j_serving_ttft_seconds_bucket", {"le": "+Inf"}, 100.0),
        ]
        assert o.counts(samples) == (5.0, 100.0)

    def test_availability_pages_once_then_recovers(self):
        pages = []
        eng = slo_mod.BurnRateEngine(
            objectives=[slo_mod.Objective(
                "availability", "availability", "dl4j_requests_total",
                target=0.999)],
            on_page=lambda name, wids: pages.append((name, wids)))
        eng.ingest(_expo("w1", 100, 0), now=0.0)
        eng.ingest(_expo("w1", 100, 50), now=10.0)
        doc = eng.evaluate(now=10.0)
        assert doc["severity"] == "page"
        (alert,) = doc["alerts"]
        assert alert["objective"] == "availability"
        assert alert["workers"] == ["w1"]
        assert pages == [("availability", ["w1"])]

        # Still breaching: severity holds, on_page does NOT re-fire.
        eng.ingest(_expo("w1", 100, 55), now=20.0)
        assert eng.evaluate(now=20.0)["severity"] == "page"
        assert len(pages) == 1

        # Healthy traffic ages the breach out of the page short window
        # (300s): burn must fire over BOTH windows, so the page clears
        # even though the long window still remembers the incident.
        for t in (400.0, 500.0, 600.0, 700.0):
            eng.ingest(_expo("w1", 100 + t, 55), now=t)
        doc = eng.evaluate(now=700.0)
        avail = [o for o in doc["objectives"]
                 if o["name"] == "availability"][0]
        assert avail["severity"] != "page"

        # A NEW sustained breach pages again (the paging set reset).
        eng.ingest(_expo("w1", 800.0, 400), now=710.0)
        eng.evaluate(now=710.0)
        assert len(pages) == 2

    def test_latency_objective_pages_with_window_scale(self):
        eng = slo_mod.BurnRateEngine(
            objectives=[slo_mod.Objective(
                "ttft_p99", "latency", "dl4j_serving_ttft_seconds",
                target=0.99, threshold_s=1.0)],
            window_scale=1.0 / 600.0)  # page windows 0.5s / 6s
        eng.ingest(_expo("w1", 0, 0, ttft=(100, 100)), now=0.0)
        eng.ingest(_expo("w1", 0, 0, ttft=(101, 200)), now=0.3)
        doc = eng.evaluate(now=0.3)
        assert doc["severity"] == "page"
        assert doc["alerts"][0]["objective"] == "ttft_p99"

    def test_counter_reset_clamps_to_zero(self):
        eng = slo_mod.BurnRateEngine(
            objectives=[slo_mod.Objective(
                "availability", "availability", "dl4j_requests_total",
                target=0.999)])
        eng.ingest(_expo("w1", 100, 20), now=0.0)
        eng.ingest(_expo("w1", 5, 0), now=10.0)  # restart: counters reset
        assert eng.evaluate(now=10.0)["severity"] == "ok"

    def test_default_objectives_cover_the_serving_slos(self):
        objs = {o.name: o for o in slo_mod.default_objectives()}
        assert set(objs) == {"availability", "ttft_p99", "itl_p99",
                             "predict_p99"}
        assert objs["itl_p99"].family == "dl4j_serving_itl_seconds"
        assert objs["predict_p99"].labels == {"route": "predict"}


# -------------------------------------------------- federation staleness


class TestFederationStaleness:
    def test_lease_expired_member_dropped_and_marked_down(self):
        agg = fed.FleetAggregator("127.0.0.1:1")
        doc = {"lost_after_s": 5.0, "detail": {
            "r-stale@127.0.0.1:59991": {"role": "replica",
                                        "lease_age_s": 99.0},
            "r-live@127.0.0.1:59992": {"role": "replica",
                                       "lease_age_s": 0.1},
        }}
        agg._client.status = lambda: doc

        members = agg.members()
        assert "r-live@127.0.0.1:59992" in members
        assert "r-stale@127.0.0.1:59991" not in members

        # One poll surfaces the staleness: the expired member is never
        # scraped but lands in the exposition as federation_up 0.
        text = agg.federate_metrics()
        assert ('dl4j_federation_up{worker_id='
                '"r-stale@127.0.0.1:59991"} 0') in text
        assert 'worker_id="r-stale@127.0.0.1:59991",' not in text


# ----------------------------------------------------- benchdiff sentinel


class TestBenchdiff:
    def _write(self, tmp_path, name, doc):
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        return str(p)

    def test_committed_files_gate_clean(self):
        from deeplearning4j_tpu.analysis import benchdiff

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        cur = os.path.join(root, "BENCH_out.json")
        base = os.path.join(root, "BASELINE.json")
        assert os.path.isfile(cur) and os.path.isfile(base)
        assert benchdiff.main([cur, base]) == 0

    def test_latency_regression_exits_nonzero(self, tmp_path):
        from deeplearning4j_tpu.analysis import benchdiff

        base = self._write(tmp_path, "base.json",
                           {"metric": "predict_p99_ms", "value": 1.0,
                            "unit": "ms"})
        cur = self._write(tmp_path, "cur.json",
                          {"metric": "predict_p99_ms", "value": 2.0,
                           "unit": "ms"})
        assert benchdiff.main([cur, base]) == 1
        # An improvement (or within-tolerance drift) gates clean.
        ok = self._write(tmp_path, "ok.json",
                         {"metric": "predict_p99_ms", "value": 0.5,
                          "unit": "ms"})
        assert benchdiff.main([ok, base]) == 0
        # Per-metric tolerance widens the band for THIS metric only.
        assert benchdiff.main([cur, base,
                               "--tol", "predict_p99_ms=1.5"]) == 0

    def test_throughput_direction_and_extra_metrics(self, tmp_path):
        from deeplearning4j_tpu.analysis import benchdiff

        base = self._write(tmp_path, "base.json", {
            "metric": "tokens_per_s", "value": 100.0,
            "extra": {"spec_accept_rate": 0.8,
                      "decode_step_ms": {"value": 5.0, "unit": "ms"}}})
        drop = self._write(tmp_path, "drop.json", {
            "metric": "tokens_per_s", "value": 50.0,
            "extra": {"spec_accept_rate": 0.8,
                      "decode_step_ms": {"value": 5.0, "unit": "ms"}}})
        assert benchdiff.main([drop, base]) == 1  # throughput fell
        rise = self._write(tmp_path, "rise.json", {
            "metric": "tokens_per_s", "value": 200.0,
            "extra": {"spec_accept_rate": 0.81,
                      "decode_step_ms": {"value": 9.0, "unit": "ms"}}})
        # Throughput up is fine; the ms extra regressed UP -> non-zero.
        assert benchdiff.main([rise, base]) == 1
        rows, regs = benchdiff.diff(json.load(open(rise)),
                                    json.load(open(base)))
        assert [r["metric"] for r in regs] == ["decode_step_ms"]

    def test_no_shared_metrics_and_bad_usage(self, tmp_path):
        from deeplearning4j_tpu.analysis import benchdiff

        a = self._write(tmp_path, "a.json", {"metric": "x", "value": 1.0})
        b = self._write(tmp_path, "b.json", {"metric": "y", "value": 1.0})
        assert benchdiff.main([a, b]) == 0
        assert benchdiff.main([a, str(tmp_path / "missing.json")]) == 2
        assert benchdiff.main([a, b, "--tol", "nonsense"]) == 2


# ------------------------------------------------------ fleet acceptance


class TestFleetSLOAcceptance:
    def test_three_replicas_two_tenants_reconcile_page_one_bundle(
            self, lm, tmp_path, monkeypatch):
        """The ISSUE 17 acceptance drill, in-process: 3 replicas x 2
        LoRA tenants under mixed traffic. All replicas share this
        process's registry and ledger, so every federated sum is 3x the
        local one — BOTH sides of the reconciliation scale together."""
        from deeplearning4j_tpu.parallel.coordinator import Coordinator
        from deeplearning4j_tpu.serving import FleetRouter
        from deeplearning4j_tpu.serving.fleet import ReplicaServer
        from deeplearning4j_tpu.serving import metrics as sm

        monkeypatch.setattr(obs.flight, "dump_dir", str(tmp_path))
        coord = Coordinator(lost_after_s=10.0).start()
        replicas, router = [], None
        try:
            for i in range(3):
                rs = ReplicaServer(coord.address, name=f"slor{i}",
                                   net=lm, replica_index=i,
                                   heartbeat_s=0.25, max_batch_size=4,
                                   decode_slots=2, kv_cache="paged",
                                   kv_page_size=8, handle_sigterm=False)
                rs.server.load_adapter("tenant-a",
                                       net=_tenant_net(lm, 1))
                rs.server.load_adapter("tenant-b",
                                       net=_tenant_net(lm, 2))
                rs.start()
                replicas.append(rs)
            # Shrink the burn windows (1/150 -> page over 2s/24s) so two
            # HTTP polls a fraction of a second apart exercise the real
            # multi-window logic.
            router = FleetRouter(coord.address, poll_interval_s=0.1,
                                 http=True,
                                 slo_window_scale=1.0 / 150.0).start()
            url = router.url

            obs.request_ledger.clear()
            d0 = _counter_total("dl4j_serving_dispatch_seconds_total",
                                model="default")

            x = np.asarray([[[t % 7] for t in range(16)]], np.int32)
            for i, rs in enumerate(replicas):
                for adapter in (None, "tenant-a", "tenant-b"):
                    rs.server.predict(x, adapter=adapter)
                rs.server.generate([1, 2, 3 + i], 4, temperature=0.0,
                                   adapter=("tenant-a", "tenant-b")[i % 2])

            # Federated accounting: /v1/tenants over the router merges
            # every replica's rows; device-seconds must reconcile with
            # the dispatch choke-point counter within 5%.
            doc = _get(url, "/v1/tenants")
            rows = doc["tenants"]
            assert {(r["model"], r["adapter"]) for r in rows} == {
                ("default", ""), ("default", "tenant-a"),
                ("default", "tenant-b")}
            n_workers = len({w for r in rows for w in r["workers"]})
            assert n_workers == 3
            fleet_total = sum(r["device_seconds"] for r in rows)
            delta = _counter_total("dl4j_serving_dispatch_seconds_total",
                                   model="default") - d0
            assert delta > 0
            # Every worker re-reports the one shared in-process ledger.
            assert abs(fleet_total - 3 * delta) <= 0.05 * (3 * delta)

            # Every replica is up in the federated exposition.
            text = router.aggregator().federate_metrics()
            for rs in replicas:
                wid = f"{rs.name}@{rs.server.host}:{rs.server.port}"
                assert f'dl4j_federation_up{{worker_id="{wid}"}} 1' in text

            # Healthy burn: no page yet.
            assert _get(url, "/fleet/slo")["severity"] == "ok"
            bundles0 = len(os.listdir(str(tmp_path)))

            # Chaos: a latency breach (first tokens at 5s >> the 1s SLO)
            # lands in the fleet's TTFT histogram...
            for _ in range(150):
                sm.TTFT_SECONDS.labels(model="default").observe(5.0)

            # ...and the NEXT burn evaluation pages on ttft_p99 over
            # both windows, naming every offending worker.
            doc = _get(url, "/fleet/slo")
            assert doc["severity"] == "page"
            alert = [a for a in doc["alerts"]
                     if a["objective"] == "ttft_p99"][0]
            assert alert["severity"] == "page"
            assert len(alert["workers"]) >= 3

            # The page froze evidence on the offenders: the router POSTed
            # every offender's /admin/flight-dump, and the per-reason
            # rate limit collapsed them into EXACTLY ONE bundle.
            bundles = [d for d in os.listdir(str(tmp_path))
                       if "slo" in d]
            assert len(os.listdir(str(tmp_path))) - bundles0 == 1
            assert len(bundles) == 1
            ledger_file = os.path.join(str(tmp_path), bundles[0],
                                       "ledger.jsonl")
            assert os.path.isfile(ledger_file)

            # Still breaching on the next poll: no second dump round
            # (the engine pages on transition, the recorder rate-limits).
            assert _get(url, "/fleet/slo")["severity"] == "page"
            assert len(os.listdir(str(tmp_path))) - bundles0 == 1
        finally:
            if router is not None:
                router.stop()
            for rs in replicas:
                try:
                    rs.drain(timeout_s=5.0)
                except Exception:
                    pass
            coord.close()
