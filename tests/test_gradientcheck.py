"""Gradient checks: central finite differences vs analytic (autodiff) gradients.

Reference analog: `deeplearning4j-core/src/test/.../gradientcheck/*` —
GradientCheckTests, CNNGradientCheckTest, BNGradientCheckTest,
LRNGradientCheckTests, GlobalPoolingGradientCheckTests, VaeGradientCheckTests,
GradientCheckTestsComputationGraph, GradientCheckTestsMasking,
LossFunctionGradientCheck. All in float64.
"""

import numpy as np
import pytest

from deeplearning4j_tpu import (
    ComputationGraph,
    MultiLayerNetwork,
    NeuralNetConfiguration,
)
from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.gradientcheck import check_gradients
from deeplearning4j_tpu.nn.conf.graph import ElementWiseVertex, MergeVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    AutoEncoder,
    BatchNormalization,
    ConvolutionLayer,
    DenseLayer,
    EmbeddingLayer,
    GlobalPoolingLayer,
    GravesBidirectionalLSTM,
    GravesLSTM,
    LocalResponseNormalization,
    OutputLayer,
    RnnOutputLayer,
    SimpleRnn,
    SubsamplingLayer,
)

EPS = 1e-6
TOL = 1e-5


from conftest import make_classification_data


def base_builder():
    return (NeuralNetConfiguration.builder()
            .seed(12345).learning_rate(0.1).updater("sgd")
            .weight_init("xavier").dtype("float64"))


def class_data(rng, n=6, nf=4, nc=3):
    return make_classification_data(rng, n=n, n_features=nf, n_classes=nc)


class TestMLPGradients:
    @pytest.mark.parametrize("act", ["sigmoid", "tanh", "relu", "elu", "softplus",
                                     "rationaltanh", "hardsigmoid", "cube"])
    def test_dense_activations(self, rng, act):
        X, Y = class_data(rng)
        conf = (base_builder().list()
                .layer(DenseLayer(n_out=5, activation=act))
                .layer(OutputLayer(n_out=3, activation="softmax", loss_function="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        assert check_gradients(net, DataSet(X, Y), epsilon=EPS, max_rel_error=TOL)

    @pytest.mark.parametrize("loss,act", [
        ("mcxent", "softmax"), ("mse", "identity"), ("mse", "tanh"),
        ("xent", "sigmoid"), ("l1", "identity"), ("negativeloglikelihood", "softmax"),
        ("kl_divergence", "sigmoid"), ("poisson", "softplus"), ("hinge", "identity"),
        ("squared_hinge", "identity"), ("cosine_proximity", "identity"),
    ])
    def test_loss_functions(self, rng, loss, act):
        X, Y = class_data(rng)
        if loss == "kl_divergence":
            Y = np.abs(rng.rand(6, 3)) + 0.1
            Y = Y / Y.sum(-1, keepdims=True)
        if loss in ("hinge", "squared_hinge"):
            Y = 2.0 * Y - 1.0
        conf = (base_builder().list()
                .layer(DenseLayer(n_out=5, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation=act, loss_function=loss))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        assert check_gradients(net, DataSet(X, Y), epsilon=EPS, max_rel_error=TOL), (loss, act)

    def test_l1_l2(self, rng):
        X, Y = class_data(rng)
        conf = (base_builder().l1(0.01).l2(0.02).list()
                .layer(DenseLayer(n_out=5, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        assert check_gradients(net, DataSet(X, Y), epsilon=EPS, max_rel_error=TOL)

    def test_embedding(self, rng):
        idx = rng.randint(0, 7, (6,)).astype("int32")
        Y = np.eye(3)[rng.randint(0, 3, 6)]
        conf = (base_builder().list()
                .layer(EmbeddingLayer(n_in=7, n_out=5, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax"))
                .build())
        net = MultiLayerNetwork(conf).init()
        assert check_gradients(net, DataSet(idx, Y), epsilon=EPS, max_rel_error=TOL)

    def test_autoencoder_supervised(self, rng):
        X, Y = class_data(rng)
        conf = (base_builder().list()
                .layer(AutoEncoder(n_out=5, activation="sigmoid"))
                .layer(OutputLayer(n_out=3, activation="softmax"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        assert check_gradients(net, DataSet(X, Y), epsilon=EPS, max_rel_error=TOL)


class TestCNNGradients:
    def test_conv_subsampling(self, rng):
        X = rng.randn(4, 8, 8, 2)
        Y = np.eye(3)[rng.randint(0, 3, 4)]
        conf = (base_builder().list()
                .layer(ConvolutionLayer(kernel_size=(3, 3), stride=(1, 1), n_out=3,
                                        activation="tanh"))
                .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2), stride=(2, 2)))
                .layer(OutputLayer(n_out=3, activation="softmax"))
                .set_input_type(InputType.convolutional(8, 8, 2)).build())
        net = MultiLayerNetwork(conf).init()
        assert check_gradients(net, DataSet(X, Y), epsilon=EPS, max_rel_error=TOL)

    @pytest.mark.parametrize("pool", ["avg", "pnorm"])
    def test_pooling_types(self, rng, pool):
        X = rng.randn(3, 6, 6, 2)
        Y = np.eye(2)[rng.randint(0, 2, 3)]
        conf = (base_builder().list()
                .layer(ConvolutionLayer(kernel_size=(2, 2), n_out=2, activation="tanh"))
                .layer(SubsamplingLayer(pooling_type=pool, kernel_size=(2, 2), stride=(2, 2)))
                .layer(OutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.convolutional(6, 6, 2)).build())
        net = MultiLayerNetwork(conf).init()
        assert check_gradients(net, DataSet(X, Y), epsilon=EPS, max_rel_error=TOL)

    def test_conv_same_mode(self, rng):
        X = rng.randn(3, 5, 5, 1)
        Y = np.eye(2)[rng.randint(0, 2, 3)]
        conf = (base_builder().list()
                .layer(ConvolutionLayer(kernel_size=(3, 3), n_out=2,
                                        convolution_mode="same", activation="tanh"))
                .layer(OutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.convolutional(5, 5, 1)).build())
        net = MultiLayerNetwork(conf).init()
        assert check_gradients(net, DataSet(X, Y), epsilon=EPS, max_rel_error=TOL)

    def test_batchnorm(self, rng):
        X = rng.randn(8, 4)
        Y = np.eye(3)[rng.randint(0, 3, 8)]
        conf = (base_builder().activation("identity").list()
                .layer(DenseLayer(n_out=5, activation="tanh"))
                .layer(BatchNormalization())
                .layer(OutputLayer(n_out=3, activation="softmax"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        # BN gradcheck uses the inference path (fixed stats), per reference
        # BNGradientCheckTest semantics (batch-stat jacobian differs).
        assert check_gradients(net, DataSet(X, Y), epsilon=EPS, max_rel_error=TOL)

    def test_lrn(self, rng):
        X = rng.randn(3, 5, 5, 6)
        Y = np.eye(2)[rng.randint(0, 2, 3)]
        conf = (base_builder().list()
                .layer(ConvolutionLayer(kernel_size=(2, 2), n_out=6, activation="tanh"))
                .layer(LocalResponseNormalization())
                .layer(OutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.convolutional(5, 5, 6)).build())
        net = MultiLayerNetwork(conf).init()
        assert check_gradients(net, DataSet(X, Y), epsilon=EPS, max_rel_error=TOL)


class TestRNNGradients:
    def test_graves_lstm(self, rng):
        X = rng.randn(3, 5, 4)
        Y = np.eye(3)[rng.randint(0, 3, (3, 5))]
        conf = (base_builder().list()
                .layer(GravesLSTM(n_out=4, activation="tanh"))
                .layer(RnnOutputLayer(n_out=3, activation="softmax", loss_function="mcxent"))
                .set_input_type(InputType.recurrent(4)).build())
        net = MultiLayerNetwork(conf).init()
        assert check_gradients(net, DataSet(X, Y), epsilon=EPS, max_rel_error=TOL)

    def test_bidirectional_lstm(self, rng):
        X = rng.randn(2, 4, 3)
        Y = np.eye(2)[rng.randint(0, 2, (2, 4))]
        conf = (base_builder().list()
                .layer(GravesBidirectionalLSTM(n_out=3, activation="tanh"))
                .layer(RnnOutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.recurrent(3)).build())
        net = MultiLayerNetwork(conf).init()
        assert check_gradients(net, DataSet(X, Y), epsilon=EPS, max_rel_error=TOL)

    def test_simple_rnn(self, rng):
        X = rng.randn(3, 4, 3)
        Y = np.eye(2)[rng.randint(0, 2, (3, 4))]
        conf = (base_builder().list()
                .layer(SimpleRnn(n_out=4, activation="tanh"))
                .layer(RnnOutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.recurrent(3)).build())
        net = MultiLayerNetwork(conf).init()
        assert check_gradients(net, DataSet(X, Y), epsilon=EPS, max_rel_error=TOL)

    def test_lstm_with_masking(self, rng):
        X = rng.randn(3, 5, 4)
        Y = np.eye(3)[rng.randint(0, 3, (3, 5))]
        mask = np.array([
            [1, 1, 1, 1, 1],
            [1, 1, 1, 0, 0],
            [1, 1, 0, 0, 0],
        ], dtype="float64")
        conf = (base_builder().list()
                .layer(GravesLSTM(n_out=4, activation="tanh"))
                .layer(RnnOutputLayer(n_out=3, activation="softmax"))
                .set_input_type(InputType.recurrent(4)).build())
        net = MultiLayerNetwork(conf).init()
        ds = DataSet(X, Y, features_mask=mask, labels_mask=mask)
        assert check_gradients(net, ds, epsilon=EPS, max_rel_error=TOL)

    def test_global_pooling_over_time(self, rng):
        X = rng.randn(3, 5, 4)
        Y = np.eye(2)[rng.randint(0, 2, 3)]
        mask = np.array([[1, 1, 1, 1, 1], [1, 1, 1, 0, 0], [1, 1, 0, 0, 0]],
                        dtype="float64")
        for pool in ["max", "avg", "sum", "pnorm"]:
            conf = (base_builder().list()
                    .layer(GravesLSTM(n_out=3, activation="tanh"))
                    .layer(GlobalPoolingLayer(pooling_type=pool))
                    .layer(OutputLayer(n_out=2, activation="softmax"))
                    .set_input_type(InputType.recurrent(4)).build())
            net = MultiLayerNetwork(conf).init()
            ds = DataSet(X, Y, features_mask=mask)
            assert check_gradients(net, ds, epsilon=EPS, max_rel_error=TOL), pool


class TestGraphGradients:
    def test_merge_vertex(self, rng):
        X, Y = class_data(rng)
        conf = (base_builder().graph_builder()
                .add_inputs("in")
                .add_layer("d1", DenseLayer(n_in=4, n_out=5, activation="tanh"), "in")
                .add_layer("d2", DenseLayer(n_in=4, n_out=4, activation="sigmoid"), "in")
                .add_vertex("merge", MergeVertex(), "d1", "d2")
                .add_layer("out", OutputLayer(n_in=9, n_out=3, activation="softmax"), "merge")
                .set_outputs("out").build())
        net = ComputationGraph(conf).init()
        assert check_gradients(net, DataSet(X, Y), epsilon=EPS, max_rel_error=TOL)

    def test_elementwise_add_residual(self, rng):
        X, Y = class_data(rng)
        conf = (base_builder().graph_builder()
                .add_inputs("in")
                .add_layer("d1", DenseLayer(n_in=4, n_out=4, activation="tanh"), "in")
                .add_vertex("add", ElementWiseVertex(op="add"), "d1", "in")
                .add_layer("out", OutputLayer(n_in=4, n_out=3, activation="softmax"), "add")
                .set_outputs("out").build())
        net = ComputationGraph(conf).init()
        assert check_gradients(net, DataSet(X, Y), epsilon=EPS, max_rel_error=TOL)

    def test_multi_output(self, rng):
        X = rng.randn(5, 4)
        Y1 = np.eye(3)[rng.randint(0, 3, 5)]
        Y2 = rng.randn(5, 2)
        conf = (base_builder().graph_builder()
                .add_inputs("in")
                .add_layer("shared", DenseLayer(n_in=4, n_out=6, activation="tanh"), "in")
                .add_layer("out1", OutputLayer(n_in=6, n_out=3, activation="softmax",
                                               loss_function="mcxent"), "shared")
                .add_layer("out2", OutputLayer(n_in=6, n_out=2, activation="identity",
                                               loss_function="mse"), "shared")
                .set_outputs("out1", "out2").build())
        net = ComputationGraph(conf).init()
        mds = MultiDataSet(features=[X], labels=[Y1, Y2])
        assert check_gradients(net, mds, epsilon=EPS, max_rel_error=TOL)

    def test_multi_input(self, rng):
        X1 = rng.randn(5, 3)
        X2 = rng.randn(5, 2)
        Y = np.eye(2)[rng.randint(0, 2, 5)]
        conf = (base_builder().graph_builder()
                .add_inputs("a", "b")
                .add_layer("da", DenseLayer(n_in=3, n_out=4, activation="tanh"), "a")
                .add_layer("db", DenseLayer(n_in=2, n_out=4, activation="tanh"), "b")
                .add_vertex("m", MergeVertex(), "da", "db")
                .add_layer("out", OutputLayer(n_in=8, n_out=2, activation="softmax"), "m")
                .set_outputs("out").build())
        net = ComputationGraph(conf).init()
        mds = MultiDataSet(features=[X1, X2], labels=[Y])
        assert check_gradients(net, mds, epsilon=EPS, max_rel_error=TOL)


class TestTransformerLayerGradients:
    """Round-5 transformer-family layers: LayerNormalization and
    PositionalEmbeddingLayer (no reference analog; gradient-checked to the
    same bar as every other layer)."""

    def test_layernorm(self, rng):
        from deeplearning4j_tpu.nn.conf.layers import LayerNormalization

        X, Y = class_data(rng)
        conf = (base_builder().list()
                .layer(DenseLayer(n_out=5, activation="tanh"))
                .layer(LayerNormalization())
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss_function="mcxent"))
                .set_input_type(InputType.feed_forward(4)).build())
        net = MultiLayerNetwork(conf).init()
        assert check_gradients(net, DataSet(X, Y), epsilon=EPS,
                               max_rel_error=TOL)

    def test_positional_embedding_sequence(self, rng):
        from deeplearning4j_tpu.nn.conf.layers import (
            LayerNormalization, PositionalEmbeddingLayer,
        )

        b, t, f, c = 3, 5, 4, 3
        X = rng.randn(b, t, f)
        Y = np.eye(c)[rng.randint(0, c, (b, t))].astype("float64")
        conf = (base_builder().list()
                .layer(PositionalEmbeddingLayer(max_length=8))
                .layer(LayerNormalization())
                .layer(RnnOutputLayer(n_out=c, activation="softmax",
                                      loss_function="mcxent"))
                .set_input_type(InputType.recurrent(f, t)).build())
        net = MultiLayerNetwork(conf).init()
        assert check_gradients(net, DataSet(X, Y), epsilon=EPS,
                               max_rel_error=TOL)
