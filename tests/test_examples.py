"""Examples stay runnable: execute the two fastest examples as real
subprocesses (the dl4j-examples analog of doc-snippet CI). The rest share
the same APIs, which the main suites cover."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize("name,expect", [
    ("csv_graph_multi_io", b"final score"),
    ("data_parallel", b"accuracy"),
])
def test_example_runs(name, expect):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", f"{name}.py")],
        capture_output=True, timeout=420, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert expect in out.stdout
