"""Clustering + t-SNE tests.

Reference analogs: `deeplearning4j-core/src/test/.../clustering/`
(`KMeansTest.java`, `KDTreeTest.java`, `VPTreeTest.java`) and
`plot/Test...Tsne`-style checks (embedding separates well-separated input
clusters, KL divergence decreases).
"""

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import KDTree, KMeansClustering, VPTree
from deeplearning4j_tpu.clustering.kdtree import knn_brute
from deeplearning4j_tpu.plot import BarnesHutTsne, Tsne


def _blobs(rng, centers, n=40, noise=0.5):
    X = np.concatenate([c + rng.randn(n, len(c)) * noise for c in centers])
    labels = np.repeat(np.arange(len(centers)), n)
    return X.astype(np.float32), labels


class TestKMeans:
    def test_recovers_blobs(self, rng):
        centers = np.array([[0., 0.], [10., 0.], [0., 10.]])
        X, labels = _blobs(np.random.RandomState(0), centers)
        cs = KMeansClustering.setup(3, max_iterations=50).apply_to(X)
        # Perfect purity on well-separated blobs.
        for b in range(3):
            a = cs.assignments[labels == b]
            assert (a == a[0]).all()
        # Each found center is near a true center.
        for c in cs.centers:
            assert np.min(np.linalg.norm(centers - c, axis=1)) < 1.0

    def test_cosine_distance(self, rng):
        r = np.random.RandomState(0)
        # Two directions, different magnitudes.
        X = np.concatenate([
            np.outer(r.rand(30) * 5 + 1, [1.0, 0.0]) + r.randn(30, 2) * 0.05,
            np.outer(r.rand(30) * 5 + 1, [0.0, 1.0]) + r.randn(30, 2) * 0.05,
        ]).astype(np.float32)
        cs = KMeansClustering.setup(2, 30, distance_function="cosine").apply_to(X)
        assert (cs.assignments[:30] == cs.assignments[0]).all()
        assert (cs.assignments[30:] == cs.assignments[30]).all()
        assert cs.assignments[0] != cs.assignments[30]

    def test_k_larger_than_points_raises(self):
        with pytest.raises(ValueError):
            KMeansClustering.setup(5).apply_to(np.zeros((3, 2), np.float32))


class TestTrees:
    def test_kdtree_matches_brute_force(self, rng):
        r = np.random.RandomState(0)
        P = r.randn(300, 4)
        tree = KDTree(P)
        for _ in range(10):
            q = r.randn(4)
            got = [i for _, i in tree.knn_indices(q, 7)]
            _, want = knn_brute(P, q[None], 7)
            assert got == list(want[0])

    def test_kdtree_incremental_insert(self):
        tree = KDTree(dims=2)
        pts = [[0, 0], [5, 5], [1, 1], [9, 9]]
        for p in pts:
            tree.insert(np.asarray(p, float))
        assert tree.size() == 4
        d, p = tree.nn(np.array([1.2, 1.2]))
        np.testing.assert_array_equal(p, [1, 1])

    def test_vptree_matches_brute_force(self, rng):
        r = np.random.RandomState(1)
        P = r.randn(300, 4)
        tree = VPTree(P)
        for _ in range(10):
            q = r.randn(4)
            got = [i for _, i in tree.knn(q, 5)]
            _, want = knn_brute(P, q[None], 5)
            assert got == list(want[0])

    def test_vptree_cosine(self):
        P = np.array([[1, 0], [2, 0.01], [0, 1], [0.01, 3]], float)
        tree = VPTree(P, distance_function="cosine")
        got = [i for _, i in tree.knn(np.array([1.0, 0.001]), 2)]
        assert set(got) == {0, 1}


class TestTsne:
    def test_separates_blobs_and_kl_decreases(self, rng):
        centers = np.zeros((3, 10))
        centers[0, 0] = 8
        centers[1, 1] = 8
        X, labels = _blobs(np.random.RandomState(0), centers, n=30, noise=0.3)
        ts = Tsne(max_iter=300, perplexity=10, seed=1)
        Y = ts.fit_transform(X)
        assert Y.shape == (90, 2)
        assert ts.kl_divergences[-1] < ts.kl_divergences[0] * 0.25
        cent = np.stack([Y[labels == b].mean(0) for b in range(3)])
        intra = np.mean([np.linalg.norm(Y[labels == b] - cent[b], axis=1).mean()
                         for b in range(3)])
        inter = np.mean([np.linalg.norm(cent[i] - cent[j])
                         for i in range(3) for j in range(i + 1, 3)])
        assert inter > 1.5 * intra, (inter, intra)

    def test_barnes_hut_alias(self, rng):
        X, _ = _blobs(np.random.RandomState(0), np.eye(3) * 5, n=10)
        bh = BarnesHutTsne(theta=0.5, max_iter=50, perplexity=5, seed=1)
        Y = bh.fit(X).Y
        assert Y.shape == (30, 2)
        assert bh.theta == 0.5

    def test_too_few_points_raises(self):
        with pytest.raises(ValueError):
            Tsne().fit_transform(np.zeros((2, 3)))


def test_kdtree_empty_queries_raise():
    from deeplearning4j_tpu.clustering.kdtree import KDTree

    t = KDTree(2)
    with pytest.raises(ValueError, match="empty KDTree"):
        t.nn(np.zeros(2))
    with pytest.raises(ValueError, match="empty KDTree"):
        t.knn(np.zeros(2), 3)


def test_kmeans_cosine_seeding_uses_cosine(rng):
    """k-means++ on cosine runs seeds by angle, not magnitude: two angular
    clusters with very different norms must still split by direction."""
    from deeplearning4j_tpu.clustering.kmeans import KMeansClustering

    a = rng.randn(40, 2) * 0.05 + np.array([1.0, 0.0])
    b = rng.randn(40, 2) * 0.05 + np.array([0.0, 1.0])
    pts = np.concatenate([a * 100.0, b * 0.01])  # extreme magnitude skew
    km = KMeansClustering(k=2, distance_function="cosine", seed=7)
    cs = km.apply_to(pts.astype(np.float64))
    assign = np.asarray(cs.assignments)
    assert len(set(assign[:40])) == 1
    assert len(set(assign[40:])) == 1
    assert assign[0] != assign[40]
