"""Parallel-package tests: distributed must equal single-machine.

Reference analog: the Spark suite's key equivalence test
`dl4j-spark/src/test/.../TestCompareParameterAveragingSparkVsSingleMachine.java`
— here stronger, because GSPMD data parallelism is per-step gradient
all-reduce, so sharded and unsharded runs execute the SAME math and must
match to float tolerance, not just "close after averaging".
"""

import numpy as np
import pytest

import jax

from deeplearning4j_tpu import MultiLayerNetwork, NeuralNetConfiguration
from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BatchNormalization,
    DenseLayer,
    GravesLSTM,
    OutputLayer,
    RnnOutputLayer,
)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.parallel import mesh as mesh_mod
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

from conftest import make_classification_data


def mlp_conf(n_in=6, n_out=3, lr=0.1, updater="sgd", l2=0.0):
    b = (NeuralNetConfiguration.builder()
         .seed(7).learning_rate(lr).updater(updater).weight_init("xavier"))
    if l2:
        b = b.l2(l2).regularization(True)
    return (b.list()
            .layer(DenseLayer(n_out=12, activation="tanh"))
            .layer(OutputLayer(n_out=n_out, activation="softmax",
                               loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(n_in))
            .build())


def make_batches(rng, n_batches=4, batch=16, n_in=6, n_out=3):
    out = []
    for _ in range(n_batches):
        X, Y = make_classification_data(rng, n=batch, n_features=n_in,
                                        n_classes=n_out)
        out.append(DataSet(X.astype("float32"), Y.astype("float32")))
    return out


def fit_single(conf, batches):
    net = MultiLayerNetwork(conf).init()
    for ds in batches:
        net.fit(ds)
    return net


class TestDataParallelEquivalence:
    def test_dp_matches_single_device(self, rng):
        """ParallelWrapper on the 8-device mesh == plain single-device fit."""
        batches = make_batches(rng)
        ref = fit_single(mlp_conf(updater="adam"), batches)

        net = MultiLayerNetwork(mlp_conf(updater="adam")).init()
        pw = ParallelWrapper(net, mesh=mesh_mod.create_mesh((8,), ("data",)))
        for ds in batches:
            pw.fit(ds)

        np.testing.assert_allclose(net.params(), ref.params(), rtol=1e-5,
                                   atol=1e-6)
        assert net.iteration == ref.iteration

    def test_dp_with_l2_matches(self, rng):
        batches = make_batches(rng, n_batches=3)
        ref = fit_single(mlp_conf(l2=1e-2), batches)
        net = MultiLayerNetwork(mlp_conf(l2=1e-2)).init()
        ParallelWrapper(net).fit(batches)
        np.testing.assert_allclose(net.params(), ref.params(), rtol=1e-5,
                                   atol=1e-6)

    def test_dp_batchnorm_matches(self, rng):
        """BN batch statistics are computed over the GLOBAL batch under GSPMD,
        so even BN training matches the unsharded run (where the reference's
        replica-averaging scheme diverges)."""
        conf = (NeuralNetConfiguration.builder()
                .seed(7).learning_rate(0.05).updater("sgd").weight_init("xavier")
                .list()
                .layer(DenseLayer(n_out=8, activation="identity"))
                .layer(BatchNormalization())
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss_function="mcxent"))
                .set_input_type(InputType.feed_forward(6))
                .build())
        conf2 = (NeuralNetConfiguration.builder()
                 .seed(7).learning_rate(0.05).updater("sgd").weight_init("xavier")
                 .list()
                 .layer(DenseLayer(n_out=8, activation="identity"))
                 .layer(BatchNormalization())
                 .layer(OutputLayer(n_out=3, activation="softmax",
                                    loss_function="mcxent"))
                 .set_input_type(InputType.feed_forward(6))
                 .build())
        batches = make_batches(rng, n_batches=3)
        ref = fit_single(conf, batches)
        net = MultiLayerNetwork(conf2).init()
        ParallelWrapper(net).fit(batches)
        np.testing.assert_allclose(net.params(), ref.params(), rtol=1e-5,
                                   atol=1e-6)

    def test_ragged_final_batch_matches_unpadded(self, rng):
        """A final batch NOT divisible by the mesh (13 on 8 devices) is padded
        + loss-masked and must produce exactly the params of the unpadded
        single-device run (`parallel/wrapper.py:_pad_dataset`)."""
        full = make_batches(rng, n_batches=2, batch=16)
        X, Y = make_classification_data(rng, n=13, n_features=6, n_classes=3)
        ragged = DataSet(X.astype("float32"), Y.astype("float32"))
        batches = full + [ragged]

        ref = fit_single(mlp_conf(), batches)
        net = MultiLayerNetwork(mlp_conf()).init()
        ParallelWrapper(net).fit(batches)
        np.testing.assert_allclose(net.params(), ref.params(), rtol=1e-5,
                                   atol=1e-6)

    def test_ragged_rnn_batch_with_time_masks(self, rng):
        """Ragged batch + 3-D labels + existing [b, t] label masks: padding
        must compose with user masks, not clobber them."""
        b, t, f, c = 11, 5, 4, 3
        X = rng.randn(b, t, f).astype("float32")
        Y = np.eye(c)[rng.randint(0, c, (b, t))].astype("float32")
        lmask = (rng.rand(b, t) > 0.2).astype("float32")
        lmask[:, 0] = 1.0  # every row keeps at least one step
        conf_fn = lambda: (NeuralNetConfiguration.builder()
                           .seed(7).learning_rate(0.05).updater("sgd")
                           .weight_init("xavier")
                           .list()
                           .layer(GravesLSTM(n_out=6, activation="tanh"))
                           .layer(RnnOutputLayer(n_out=c, activation="softmax",
                                                 loss_function="mcxent"))
                           .set_input_type(InputType.recurrent(f))
                           .build())
        ds = DataSet(X, Y, None, lmask)
        ref = fit_single(conf_fn(), [ds])
        net = MultiLayerNetwork(conf_fn()).init()
        ParallelWrapper(net).fit(ds)
        np.testing.assert_allclose(net.params(), ref.params(), rtol=1e-5,
                                   atol=1e-6)


class TestTbpttParallel:
    def test_tbptt_wrapper_matches_single(self, rng):
        """tBPTT through the wrapper must (a) actually chunk — the wrapper
        dispatches through the same backprop-type logic as fit() — and
        (b) keep the reference divide-by-minibatch divisor even for chunks
        where a short sequence's mask is entirely zero, composed with
        data-parallel padding (6 rows on 8 devices)."""
        b, t, f, c = 6, 20, 4, 3
        X = rng.randn(b, t, f).astype("float32")
        Y = np.eye(c)[rng.randint(0, c, (b, t))].astype("float32")
        lmask = np.ones((b, t), "float32")
        lmask[0, 5:] = 0.0  # sequence 0 is length 5: fully masked in chunk 2

        def conf_fn():
            return (NeuralNetConfiguration.builder()
                    .seed(7).learning_rate(0.05).updater("sgd")
                    .weight_init("xavier")
                    .list()
                    .layer(GravesLSTM(n_out=6, activation="tanh"))
                    .layer(RnnOutputLayer(n_out=c, activation="softmax",
                                          loss_function="mcxent"))
                    .set_input_type(InputType.recurrent(f))
                    .backprop_type("truncatedbptt")
                    .t_bptt_forward_length(10)
                    .build())

        ds = DataSet(X, Y, None, lmask)
        ref = fit_single(conf_fn(), [ds])
        assert ref.iteration == 1  # one tBPTT pass counts one iteration
        net = MultiLayerNetwork(conf_fn()).init()
        ParallelWrapper(net).fit(ds)
        assert net.iteration == 1
        np.testing.assert_allclose(net.params(), ref.params(), rtol=1e-5,
                                   atol=1e-6)


class TestTensorParallelEquivalence:
    def test_tp_matches_replicated(self, rng):
        """Model-axis column sharding of the dense weights must not change the
        math — XLA inserts the collectives; params stay numerically equal."""
        conf_fn = lambda: (NeuralNetConfiguration.builder()
                           .seed(7).learning_rate(0.1).updater("sgd")
                           .weight_init("xavier")
                           .list()
                           .layer(DenseLayer(n_out=32, activation="tanh"))
                           .layer(DenseLayer(n_out=32, activation="relu"))
                           .layer(OutputLayer(n_out=3, activation="softmax",
                                              loss_function="mcxent"))
                           .set_input_type(InputType.feed_forward(6))
                           .build())
        batches = make_batches(rng, n_batches=3, batch=8)
        ref = fit_single(conf_fn(), batches)

        mesh = mesh_mod.create_mesh((4, 2), ("data", "model"))
        net = MultiLayerNetwork(conf_fn()).init()
        mesh_mod.shard_params(net, mesh, model_axis="model",)
        # min_shard_size guard: make sure something actually sharded
        shardings = mesh_mod.param_shardings(net.params_tree, mesh,
                                             model_axis="model",
                                             min_shard_size=64)
        specs = jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(lambda s: s.spec, shardings,
                                   is_leaf=lambda x: hasattr(x, "spec")))
        assert any("model" in str(s) for s in map(str, specs)), specs
        pw = ParallelWrapper(net, mesh=mesh)
        for ds in batches:
            pw.fit(ds)
        np.testing.assert_allclose(net.params(), ref.params(), rtol=1e-5,
                                   atol=1e-6)


class TestParallelComputationGraph:
    def test_graph_dp_matches_single(self, rng):
        """ParallelWrapper must drive a ComputationGraph (reference supports
        both engines, `ParallelWrapper.java:322/:151`)."""
        def graph_conf():
            return (NeuralNetConfiguration.builder()
                    .seed(7).learning_rate(0.1).updater("sgd")
                    .weight_init("xavier")
                    .graph_builder()
                    .add_inputs("in")
                    .add_layer("d", DenseLayer(n_out=10, activation="tanh"), "in")
                    .add_layer("out", OutputLayer(n_out=3, activation="softmax",
                                                  loss_function="mcxent"), "d")
                    .set_outputs("out")
                    .set_input_types(InputType.feed_forward(6))
                    .build())

        batches = make_batches(rng, n_batches=2, batch=16)
        ref = ComputationGraph(graph_conf()).init()
        for ds in batches:
            ref.fit(ds)

        net = ComputationGraph(graph_conf()).init()
        pw = ParallelWrapper(net)
        # drive with a ragged MultiDataSet too: pads + masks per output
        X, Y = make_classification_data(rng, n=13, n_features=6, n_classes=3)
        ragged = MultiDataSet(features=[X.astype("float32")],
                              labels=[Y.astype("float32")])
        ref.fit(ragged)
        for ds in batches:
            pw.fit(ds)
        pw.fit(ragged)

        np.testing.assert_allclose(net.params(), ref.params(), rtol=1e-5,
                                   atol=1e-6)


class TestShardedEvaluation:
    """`parallel/evaluation.py` must agree exactly with the host-side
    `net.evaluate` (reference: Spark distributed evaluation merges to the
    same numbers as local eval)."""

    def _trained_net(self, rng, n=48, f=4, c=3):
        X = rng.randn(n, f).astype("float64")
        Y = np.eye(c)[rng.randint(0, c, n)].astype("float64")
        net = MultiLayerNetwork(
            NeuralNetConfiguration.builder()
            .seed(3).learning_rate(0.1).updater("sgd").weight_init("xavier")
            .list()
            .layer(DenseLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=c, activation="softmax",
                               loss_function="mcxent"))
            .set_input_type(InputType.feed_forward(f))
            .build()).init()
        net.fit(DataSet(X, Y))
        return net, X, Y

    def test_matches_host_eval(self, rng):
        from deeplearning4j_tpu.parallel.evaluation import sharded_evaluate

        net, X, Y = self._trained_net(rng)
        ref = net.evaluate(DataSet(X, Y))
        ev = sharded_evaluate(net, DataSet(X, Y))
        np.testing.assert_array_equal(ev.confusion.matrix, ref.confusion.matrix)
        assert ev.total == ref.total
        assert ev.accuracy() == ref.accuracy()

    def test_ragged_batch_and_topn(self, rng):
        # 45 rows on 8 devices forces padding; padded rows must not count.
        from deeplearning4j_tpu.parallel.evaluation import sharded_evaluate

        net, X, Y = self._trained_net(rng, n=45)
        ref = net.evaluate(DataSet(X, Y), top_n=2)
        ev = sharded_evaluate(net, DataSet(X, Y), top_n=2)
        assert ev.total == 45 == ref.total
        np.testing.assert_array_equal(ev.confusion.matrix, ref.confusion.matrix)
        assert ev.top_n_accuracy() == ref.top_n_accuracy()

    def test_time_series_with_mask(self, rng):
        from deeplearning4j_tpu.parallel.evaluation import sharded_evaluate

        b, t, f, c = 8, 6, 4, 3
        X = rng.randn(b, t, f).astype("float64")
        Y = np.eye(c)[rng.randint(0, c, (b, t))].astype("float64")
        lmask = (rng.rand(b, t) > 0.3).astype("float64")
        net = MultiLayerNetwork(
            NeuralNetConfiguration.builder()
            .seed(3).learning_rate(0.1).updater("sgd").weight_init("xavier")
            .list()
            .layer(GravesLSTM(n_out=6, activation="tanh"))
            .layer(RnnOutputLayer(n_out=c, activation="softmax",
                                  loss_function="mcxent"))
            .set_input_type(InputType.recurrent(f))
            .build()).init()
        ds = DataSet(X, Y, None, lmask)
        ref = net.evaluate(ds)
        ev = sharded_evaluate(net, ds)
        np.testing.assert_array_equal(ev.confusion.matrix, ref.confusion.matrix)
        assert ev.total == ref.total

    def test_wrapper_entry_and_merge(self, rng):
        from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper

        net, X, Y = self._trained_net(rng)
        ref = net.evaluate(DataSet(X, Y))
        # Two halves evaluated separately then merged == whole.
        pw = ParallelWrapper(net)
        e1 = pw.evaluate(DataSet(X[:24], Y[:24]))
        e2 = pw.evaluate(DataSet(X[24:], Y[24:]))
        merged = e1.merge(e2)
        np.testing.assert_array_equal(merged.confusion.matrix,
                                      ref.confusion.matrix)
        assert merged.accuracy() == ref.accuracy()

    def test_computation_graph_sharded_eval(self, rng):
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        from deeplearning4j_tpu.parallel.evaluation import sharded_evaluate
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        n, f, c = 40, 4, 3
        X = rng.randn(n, f).astype("float64")
        Y = np.eye(c)[rng.randint(0, c, n)].astype("float64")
        gb = (NeuralNetConfiguration.builder()
              .seed(2).learning_rate(0.1).updater("sgd").weight_init("xavier")
              .graph_builder()
              .add_inputs("in")
              .add_layer("d", DenseLayer(n_out=8, activation="tanh"), "in")
              .add_layer("out", OutputLayer(n_out=c, activation="softmax",
                                            loss_function="mcxent"), "d")
              .set_outputs("out"))
        gb.set_input_types(InputType.feed_forward(f))
        net = ComputationGraph(gb.build()).init()
        mds = MultiDataSet(features=[X], labels=[Y])
        net.fit(mds)
        ref = net.evaluate(mds)
        ev = sharded_evaluate(net, mds)
        np.testing.assert_array_equal(ev.confusion.matrix, ref.confusion.matrix)
        assert ev.total == ref.total == n


# ----------------------------------------------- model-parallel sharding
# PR 20 (ISSUE 20): head-aware tensor-parallel layouts, the sharding
# debug surface, and the acceptance property — n-way model-parallel paged
# decode is greedy-identical to the unsharded stepper.


class TestModelParallelSharding:
    V, T, D, HEADS, CAP, PAGE = 32, 16, 16, 4, 32, 8

    def _lm(self, seed=321):
        from deeplearning4j_tpu.models import zoo

        conf = zoo.transformer_lm(vocab_size=self.V, t=self.T,
                                  d_model=self.D, n_heads=self.HEADS,
                                  n_blocks=1, decode_cache_length=self.CAP,
                                  seed=seed)
        return ComputationGraph(conf).init()

    def _mesh(self, ways=4):
        n = len(jax.devices())
        assert n % ways == 0
        return mesh_mod.create_mesh((n // ways, ways), ("data", "model"))

    def test_head_aware_attention_and_mlp_specs(self):
        from jax.sharding import PartitionSpec as P

        net = self._lm()
        mesh = self._mesh(4)
        ps = mesh_mod.param_shardings(net.params_tree, mesh, "model",
                                      net=net)
        attn = {k: s.spec for k, s in ps["attn0"].items()}
        # Megatron layout: QKV column-parallel on heads, Wo row-parallel,
        # output bias replicated (added after the all-reduce).
        assert attn["Wq"] == P(None, "model")
        assert attn["Wk"] == P(None, "model")
        assert attn["Wv"] == P(None, "model")
        assert attn["qB"] == P("model")
        assert attn["Wo"] == P("model", None)
        assert attn["oB"] == P()
        # MLP: up-projection column-split, down-projection row-split.
        assert ps["ff1_0"]["W"].spec == P(None, "model")
        assert ps["ff1_0"]["b"].spec == P("model")
        assert ps["ffn0"]["W"].spec == P("model", None)
        assert ps["ffn0"]["b"].spec == P()
        # Embeddings replicate on purpose (decode gathers one row/token).
        assert all(s.spec == P() for s in ps["emb"].values())

    def test_misaligned_heads_fall_back_to_replicated(self):
        from jax.sharding import PartitionSpec as P

        net = self._lm()
        mesh = mesh_mod.create_mesh((1, 8), ("data", "model"))
        # 4 heads over an 8-way axis would slice through a head: the
        # attention rule declines, and at these sizes (< min_shard_size)
        # the generic rule replicates.
        ps = mesh_mod.param_shardings(net.params_tree, mesh, "model",
                                      net=net)
        assert ps["attn0"]["Wq"].spec == P()

    def test_describe_shardings_and_replicated_counter(self):
        from deeplearning4j_tpu import observability as _obs

        # n_out 46 < n_in 50 and 50 % 4 != 0: no dense rule, and
        # 50*46=2300 >= 2048 elements — a LARGE leaf left replicated.
        conf = (NeuralNetConfiguration.builder()
                .seed(7).learning_rate(0.1).updater("sgd")
                .weight_init("xavier").list()
                .layer(DenseLayer(n_out=46, activation="tanh"))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss_function="mcxent"))
                .set_input_type(InputType.feed_forward(50))
                .build())
        net = MultiLayerNetwork(conf).init()
        mesh = self._mesh(4)
        rows = mesh_mod.describe_shardings(net, mesh, "model")
        big = [r for r in rows if r["large_replicated"]]
        assert len(big) == 1 and big[0]["shape"] == (50, 46)
        assert all({"path", "shape", "bytes", "spec", "replicated",
                    "large_replicated"} <= set(r) for r in rows)

        fam = _obs.metrics.get_family("dl4j_params_replicated_leaves")
        before = sum(c.get() for c in fam.children())
        mesh_mod.shard_params(net, mesh, model_axis="model")
        after = sum(c.get() for c in fam.children())
        assert after == before + 1

    def test_kv_page_sharding_pins_head_dim(self):
        from jax.sharding import PartitionSpec as P

        mesh = self._mesh(4)
        s = mesh_mod.kv_page_sharding(mesh, 4, "model")
        assert tuple(s.spec) == (None, None, "model", None)
        assert s.spec[2] == "model"
        unsharded = mesh_mod.kv_page_sharding(mesh, 4, None)
        assert all(d is None for d in unsharded.spec)
        assert mesh_mod.axis_sharding(mesh, 2, 1, "model").spec == P(
            None, "model")

    def test_sharded_paged_decode_matches_unsharded(self):
        """The PR's acceptance property: 4-way tensor-parallel paged
        decode produces the SAME greedy tokens as the unsharded stepper,
        per-chip param+KV bytes shrink, and page storage stays pinned to
        its head partitioning across steps."""
        from deeplearning4j_tpu.models.zoo import PagedDecodeStepper
        from deeplearning4j_tpu.parallel.context import ParallelContext
        from deeplearning4j_tpu.serving.host import per_chip_bytes

        ref_net, sh_net = self._lm(), self._lm()
        mesh = self._mesh(4)
        ctx = ParallelContext(mesh=mesh, model_axis="model")
        mesh_mod.shard_params(sh_net, mesh, model_axis="model")

        ref = PagedDecodeStepper(ref_net, 2, page_size=self.PAGE)
        sh = PagedDecodeStepper(sh_net, 2, page_size=self.PAGE,
                                context=ctx)
        prompt = [1, 2, 3, 4, 5]
        p_r, st_r, n_r = ref.prefill(prompt)
        p_s, st_s, n_s = sh.prefill(prompt)
        np.testing.assert_allclose(p_r, p_s, atol=1e-5)
        ref.install(0, st_r, n_r)
        sh.install(0, st_s, n_s)
        tok_r = tok_s = int(np.argmax(p_r))
        assert tok_r == int(np.argmax(p_s))
        for _ in range(12):
            d_r = ref.step([tok_r, 0])
            d_s = sh.step([tok_s, 0])
            np.testing.assert_allclose(d_r[0], d_s[0], atol=1e-5)
            tok_r, tok_s = int(np.argmax(d_r[0])), int(np.argmax(d_s[0]))
            assert tok_r == tok_s

        # Page storage kept its head partitioning through the scatters.
        kp = sh._state["attn0"]["k_pages"]
        assert "model" in str(kp.sharding.spec)
        # Per-chip residency actually shrank: params + KV pages.
        import jax as _jax
        global_params = sum(
            l.nbytes for l in _jax.tree_util.tree_leaves(sh_net.params_tree))
        assert per_chip_bytes(sh_net.params_tree) < 0.6 * global_params
        kv = {"k": kp, "v": sh._state["attn0"]["v_pages"]}
        global_kv = kp.nbytes + kv["v"].nbytes
        assert per_chip_bytes(kv) <= 0.3 * global_kv + 1
